"""DQN: off-policy Q-learning with replay, double-Q targets, and a target
network.

Role-equivalent to the reference's DQN (new API stack)
(reference: rllib/algorithms/dqn/dqn.py training_step: sample with
epsilon-greedy -> add to EpisodeReplayBuffer -> sample train batches ->
Learner TD update with double-Q + target net -> periodic target sync ->
weight sync to env runners) — TPU-first: the TD update is one jitted
function (online+target params both live on device; under a Mesh the batch
shards over dp and XLA inserts the gradient psum), and exploration stays on
CPU env-runner actors.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, NamedTuple, Optional

import numpy as np

import ray_tpu
from .env_runner import EnvRunner
from .replay import ReplayBuffer


class QParams(NamedTuple):
    w1: Any
    b1: Any
    w2: Any
    b2: Any
    w3: Any
    b3: Any


def init_q(obs_size: int, num_actions: int, hidden: int = 64,
           seed: int = 0) -> QParams:
    import jax
    import jax.numpy as jnp

    k = jax.random.split(jax.random.PRNGKey(seed), 3)
    he = jax.nn.initializers.he_normal()
    return QParams(
        w1=he(k[0], (obs_size, hidden), jnp.float32),
        b1=jnp.zeros(hidden),
        w2=he(k[1], (hidden, hidden), jnp.float32),
        b2=jnp.zeros(hidden),
        w3=jax.nn.initializers.orthogonal(0.01)(
            k[2], (hidden, num_actions), jnp.float32),
        b3=jnp.zeros(num_actions),
    )


def q_forward(params: QParams, obs):
    import jax.numpy as jnp

    h = jnp.maximum(obs @ params.w1 + params.b1, 0.0)
    h = jnp.maximum(h @ params.w2 + params.b2, 0.0)
    return h @ params.w3 + params.b3


class DQNConfig:
    """Fluent config (reference: algorithm_config.py AlgorithmConfig)."""

    def __init__(self):
        self.env_spec: Any = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 32
        self.lr = 5e-4
        self.gamma = 0.99
        self.hidden = 64
        self.buffer_size = 50_000
        self.train_batch_size = 64
        self.num_updates_per_iteration = 64
        self.target_update_freq = 500       # gradient steps between syncs
        self.learning_starts = 1_000        # env steps before updates begin
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 10_000   # env steps to anneal over
        self.grad_clip = 10.0
        self.seed = 0
        self.mesh = None

    def environment(self, env: Any) -> "DQNConfig":
        self.env_spec = env
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 8,
                    rollout_fragment_length: int = 32) -> "DQNConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "DQNConfig":
        for name, val in kwargs.items():
            if not hasattr(self, name):
                raise TypeError(f"unknown DQN config field {name!r}")
            setattr(self, name, val)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class DQNLearner:
    """Online + target params; jitted double-DQN TD update."""

    def __init__(self, obs_size: int, num_actions: int, *, lr: float,
                 gamma: float, grad_clip: float, hidden: int, seed: int,
                 mesh=None):
        import jax
        import jax.numpy as jnp
        import optax

        self.params = init_q(obs_size, num_actions, hidden, seed)
        self.target_params = self.params
        self.tx = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adam(lr),
        )
        self.opt_state = self.tx.init(self.params)
        tx = self.tx

        def loss_fn(params, target_params, batch):
            q = q_forward(params, batch["obs"])
            q_sa = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            # Double DQN: online net picks a', target net evaluates it
            # (reference: dqn learner uses double_q by default).
            next_a = jnp.argmax(q_forward(params, batch["next_obs"]), axis=-1)
            next_q = jnp.take_along_axis(
                q_forward(target_params, batch["next_obs"]),
                next_a[:, None], axis=1)[:, 0]
            target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * next_q
            td = q_sa - jax.lax.stop_gradient(target)
            # Huber loss keeps early-training TD spikes from blowing up Adam.
            loss = jnp.mean(jnp.where(
                jnp.abs(td) <= 1.0, 0.5 * td ** 2, jnp.abs(td) - 0.5))
            return loss, {"td_error_mean": jnp.mean(jnp.abs(td)),
                          "qf_mean": jnp.mean(q_sa)}

        from ..devtools import jitguard

        jitguard.register_program("dqn_update")

        def update(params, target_params, opt_state, batch):
            # Trace-time only: joins the recompile sentinel (RT_DEBUG_JIT).
            jitguard.bump("dqn_update", jitguard.signature_of(batch))
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["loss"] = loss
            return params, opt_state, aux

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            batch_sh = NamedSharding(mesh, P(("dp", "fsdp")))
            repl = NamedSharding(mesh, P())
            self._update = jax.jit(
                update,
                in_shardings=(repl, repl, repl,
                              {k: batch_sh for k in
                               ("obs", "next_obs", "actions", "rewards",
                                "dones")}),
                out_shardings=(repl, repl, None),
            )
        else:
            self._update = jax.jit(update)

    def get_weights(self):
        import jax
        import numpy as np

        return list(jax.tree.map(np.asarray, self.params))

    def update_raw(self, batch: Dict[str, np.ndarray]):
        """One TD update, aux left ON DEVICE: the K-updates-per-iteration
        loop in :meth:`DQN.train` calls this so the host never blocks on
        loss readback mid-loop (rtlint RT010) — only the loop's last aux
        is converted, once, by the caller."""
        import jax.numpy as jnp

        mb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, aux = self._update(
            self.params, self.target_params, self.opt_state, mb)
        return aux

    def update_from_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        aux = self.update_raw(batch)
        # THE readback point for one-off callers (single update -> floats).
        return {k: float(v) for k, v in aux.items()}

    def sync_target(self):
        self.target_params = self.params


class DQN:
    """The Algorithm: one train() = sample -> replay -> K TD updates -> sync."""

    def __init__(self, config: DQNConfig):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.config = config
        self.runners = [
            EnvRunner.remote(config.env_spec, config.num_envs_per_runner,
                             seed=config.seed + i)
            for i in range(config.num_env_runners)
        ]
        info = ray_tpu.get(self.runners[0].env_info.remote())
        self.learner = DQNLearner(
            info["observation_size"], info["num_actions"],
            lr=config.lr, gamma=config.gamma, grad_clip=config.grad_clip,
            hidden=config.hidden, seed=config.seed, mesh=config.mesh,
        )
        self.buffer = ReplayBuffer(
            config.buffer_size, info["observation_size"], seed=config.seed)
        self._sync_weights()
        self.iteration = 0
        self.total_env_steps = 0
        self.total_updates = 0
        self._recent_returns: List[float] = []

    def _sync_weights(self):
        ref = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([r.set_q_weights.remote(ref) for r in self.runners])

    def epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self.total_env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        eps = self.epsilon()
        samples = ray_tpu.get([
            r.sample_transitions.remote(cfg.rollout_fragment_length, eps)
            for r in self.runners
        ])
        n_steps = 0
        for s in samples:
            self.buffer.add_batch(s)
            n_steps += len(s["actions"])
            self._recent_returns.extend(s["episode_returns"].tolist())
        self._recent_returns = self._recent_returns[-100:]
        self.total_env_steps += n_steps

        metrics: Dict[str, float] = {}
        if self.total_env_steps >= cfg.learning_starts:
            last_aux = None
            for _ in range(cfg.num_updates_per_iteration):
                last_aux = self.learner.update_raw(
                    self.buffer.sample(cfg.train_batch_size))
                self.total_updates += 1
                if self.total_updates % cfg.target_update_freq == 0:
                    self.learner.sync_target()
            self._sync_weights()
            if last_aux is not None:
                # ONE host sync after the K TD updates (rtlint RT010):
                # the devices pipeline the whole update burst instead of
                # stalling on each loss readback.
                metrics = {k: float(v) for k, v in last_aux.items()}

        self.iteration += 1
        wall = time.perf_counter() - t0
        return {
            "training_iteration": self.iteration,
            "num_env_steps_sampled": n_steps,
            "num_env_steps_sampled_lifetime": self.total_env_steps,
            "num_gradient_updates_lifetime": self.total_updates,
            "episode_return_mean": (
                float(np.mean(self._recent_returns))
                if self._recent_returns else float("nan")
            ),
            "epsilon": eps,
            "env_steps_per_sec": n_steps / max(wall, 1e-9),
            **metrics,
        }

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    @classmethod
    def as_trainable(cls, config: DQNConfig, stop_iters: int = 100,
                     stop_reward: Optional[float] = None):
        """Function trainable for ray_tpu.tune (reference: Algorithm is a
        Trainable)."""

        def trainable(tune_config):
            from ray_tpu import tune as rt_tune

            algo = cls(config)
            try:
                result: Dict[str, Any] = {}
                for _ in range(stop_iters):
                    result = algo.train()
                    rt_tune.report(result)
                    if (stop_reward is not None
                            and result["episode_return_mean"] >= stop_reward):
                        break
                return result
            finally:
                algo.stop()

        return trainable
