"""PPO Learner: jitted clip-objective SGD in pure JAX.

Role-equivalent to the reference's Learner/TorchLearner
(reference: rllib/core/learner/learner.py:116 compute_gradients:448 /
apply_gradients:570; ppo_torch_learner computes the clip loss) — TPU-first:
the update is one jitted function; under a Mesh the batch shards over
dp/fsdp and XLA inserts the gradient psums (instead of DDP allreduce,
reference: torch_learner.py:498 TorchDDPRLModule).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class PolicyParams(NamedTuple):
    """Separate actor and critic MLPs: with a shared torso, the unnormalized
    value loss (returns are O(episode length)) swamps the policy gradient
    (reference: rllib default models use separate value networks unless
    vf_share_layers is set)."""

    pi_w1: Any
    pi_b1: Any
    pi_w2: Any
    pi_b2: Any
    pi_w3: Any
    pi_b3: Any
    v_w1: Any
    v_b1: Any
    v_w2: Any
    v_b2: Any
    v_w3: Any
    v_b3: Any


def init_policy(obs_size: int, num_actions: int, hidden: int = 64,
                seed: int = 0) -> PolicyParams:
    k = jax.random.split(jax.random.PRNGKey(seed), 6)
    he = jax.nn.initializers.orthogonal(np.sqrt(2))
    return PolicyParams(
        pi_w1=he(k[0], (obs_size, hidden), jnp.float32),
        pi_b1=jnp.zeros(hidden),
        pi_w2=he(k[1], (hidden, hidden), jnp.float32),
        pi_b2=jnp.zeros(hidden),
        pi_w3=jax.nn.initializers.orthogonal(0.01)(
            k[2], (hidden, num_actions), jnp.float32),
        pi_b3=jnp.zeros(num_actions),
        v_w1=he(k[3], (obs_size, hidden), jnp.float32),
        v_b1=jnp.zeros(hidden),
        v_w2=he(k[4], (hidden, hidden), jnp.float32),
        v_b2=jnp.zeros(hidden),
        v_w3=jax.nn.initializers.orthogonal(1.0)(
            k[5], (hidden, 1), jnp.float32),
        v_b3=jnp.zeros(1),
    )


def policy_forward(params: PolicyParams, obs: jnp.ndarray):
    """Returns (logits, value)."""
    h = jnp.tanh(obs @ params.pi_w1 + params.pi_b1)
    h = jnp.tanh(h @ params.pi_w2 + params.pi_b2)
    logits = h @ params.pi_w3 + params.pi_b3
    hv = jnp.tanh(obs @ params.v_w1 + params.v_b1)
    hv = jnp.tanh(hv @ params.v_w2 + params.v_b2)
    value = (hv @ params.v_w3 + params.v_b3)[..., 0]
    return logits, value


def sample_categorical(logits, rng: np.random.Generator):
    """Gumbel-max action sampling on host + logp of the chosen actions —
    the shared per-step inference core of every env runner (numpy rng keeps
    rollouts reproducible and avoids host<->device PRNG churn per step).

    Returns (actions [N] int32, logp [N] float32)."""
    logits = np.asarray(logits)
    gumbel = -np.log(-np.log(rng.random(logits.shape) + 1e-12) + 1e-12)
    actions = np.argmax(logits + gumbel, axis=-1).astype(np.int32)
    logp_all = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    logp = np.take_along_axis(
        np.asarray(logp_all), actions[:, None], axis=1)[:, 0]
    return actions, logp


def compute_gae(rewards: np.ndarray, values: np.ndarray,
                bootstrap_values: np.ndarray, dones: np.ndarray,
                gamma: float, lam: float):
    """Generalized advantage estimation over [T, N] rollouts (reference:
    rllib postprocessing compute_gae_for_sample_batch).

    ``bootstrap_values[t]`` is V(s_{t+1}) with episode semantics applied:
    0 where terminated, V(true pre-reset next state) where truncated,
    V(next row) otherwise — so time-limit truncation doesn't bias values.
    ``dones`` (terminated|truncated) cuts the GAE recursion."""
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    for t in range(T - 1, -1, -1):
        ended = dones[t].astype(np.float32)
        delta = rewards[t] + gamma * bootstrap_values[t] - values[t]
        last_gae = delta + gamma * lam * (1.0 - ended) * last_gae
        adv[t] = last_gae
    returns = adv + values
    return adv, returns


class PPOLearner:
    """Holds params + optimizer state; update() runs clipped-PPO epochs."""

    def __init__(
        self,
        obs_size: int,
        num_actions: int,
        *,
        lr: float = 3e-4,
        clip_param: float = 0.2,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        grad_clip: float = 0.5,
        hidden: int = 64,
        seed: int = 0,
        mesh=None,
        model=None,
    ):
        # Pluggable architecture (reference: rl_module.py — the learner is
        # model-agnostic).  Default = the classic separate-torso MLP; pass a
        # models.CNNModel for image observations.
        if model is None:
            from .models import MLPModel

            model = MLPModel((obs_size,), num_actions, hidden)
        self.model = model
        self.params = model.init(seed)
        self.tx = optax.chain(
            optax.clip_by_global_norm(grad_clip),
            optax.adam(lr, eps=1e-5),
        )
        self.opt_state = self.tx.init(self.params)
        self.clip_param = clip_param
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.mesh = mesh
        self._update = self._build_update()

    def _build_update(self):
        clip, vf_c, ent_c = self.clip_param, self.vf_coeff, self.entropy_coeff
        tx = self.tx

        model = self.model

        def loss_fn(params, batch):
            logits, value = model.apply(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv,
            ).mean()
            vf = 0.5 * jnp.mean((value - batch["returns"]) ** 2)
            entropy = -jnp.mean(
                jnp.sum(jax.nn.softmax(logits) * logp_all, axis=-1)
            )
            total = pg + vf_c * vf - ent_c * entropy
            return total, {"policy_loss": pg, "vf_loss": vf,
                           "entropy": entropy}

        from ..devtools import jitguard

        jitguard.register_program("ppo_update")

        def update(params, opt_state, batch):
            # Trace-time only: joins the recompile sentinel (RT_DEBUG_JIT)
            # so a post-warmup shape/dtype drift in the minibatch raises
            # at the stray trace instead of silently recompiling.
            jitguard.bump("ppo_update", jitguard.signature_of(batch))
            (total, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            aux["total_loss"] = total
            return params, opt_state, aux

        if self.mesh is not None:
            # Data-parallel sharded update: batch rows split over dp+fsdp,
            # params replicated; XLA inserts the gradient psum (the DDP
            # allreduce analog, but compiled).
            from jax.sharding import NamedSharding, PartitionSpec as P

            batch_sh = NamedSharding(self.mesh, P(("dp", "fsdp")))
            repl = NamedSharding(self.mesh, P())
            return jax.jit(
                update,
                in_shardings=(repl, repl,
                              {k: batch_sh for k in
                               ("obs", "actions", "logp_old", "advantages",
                                "returns")}),
                out_shardings=(repl, repl, None),
            )
        return jax.jit(update)

    # -- API ----------------------------------------------------------------

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)

    def update_from_batch(
        self,
        batch: Dict[str, np.ndarray],
        *,
        num_epochs: int = 10,
        minibatch_size: int = 128,
        seed: int = 0,
    ) -> Dict[str, float]:
        """Minibatch SGD over the rollout batch (reference:
        learner.py:922 update_from_batch minibatch loop)."""
        n = len(batch["obs"])
        adv = batch["advantages"]
        batch = dict(batch)
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        rng = np.random.default_rng(seed)
        last_aux = None
        for _ in range(num_epochs):
            order = rng.permutation(n)
            for start in range(0, n, minibatch_size):
                idx = order[start:start + minibatch_size]
                if len(idx) < minibatch_size and start > 0:
                    break  # drop ragged tail (keeps shapes static for jit)
                mb = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self.params, self.opt_state, aux = self._update(
                    self.params, self.opt_state, mb
                )
                last_aux = aux
        # ONE host sync, after the epochs: float()-ing aux inside the
        # minibatch loop blocked on device work every step (rtlint RT010)
        # — SGD should only wait for the device when the metrics are
        # actually read.
        if last_aux is None:
            return {}
        return {k: float(v) for k, v in last_aux.items()}
