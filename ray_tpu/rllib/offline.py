"""Offline RL: JSONL sample IO, behavior cloning, off-policy estimators.

Role-equivalent to the reference's offline stack (reference:
rllib/offline/json_reader.py:227 JsonReader — JSONL sample batches,
shuffled iteration; json_writer.py — episode batches to timestamped JSONL;
offline/estimators/importance_sampling.py + weighted_importance_sampling.py
— per-episode IS/WIS value estimates; algorithms/bc/bc.py — behavior
cloning as the marquee offline algorithm).

The on-disk format is JSONL where each line is one flat sample batch
(columns -> lists), so files stream without loading whole datasets, shard
across ray_tpu.data tasks, and stay human-readable.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


class JsonWriter:
    """Append sample batches to a JSONL file (reference: json_writer.py —
    one compressed JSON batch per line under a timestamped name)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        row = {k: np.asarray(v).tolist() for k, v in batch.items()}
        self._f.write(json.dumps(row) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class JsonReader:
    """Stream sample batches back from JSONL files (reference:
    json_reader.py:227 next() returns one batch per call, cycling and
    shuffling across input files)."""

    def __init__(self, paths, *, shuffle: bool = True, seed: int = 0):
        if isinstance(paths, str):
            paths = [paths]
        self.paths = list(paths)
        self.shuffle = shuffle
        self.rng = np.random.default_rng(seed)
        self._batches: Optional[List[Dict[str, np.ndarray]]] = None

    def _load(self) -> List[Dict[str, np.ndarray]]:
        if self._batches is None:
            out = []
            for p in self.paths:
                with open(p, encoding="utf-8") as f:
                    for line in f:
                        if line.strip():
                            row = json.loads(line)
                            out.append({
                                k: np.asarray(v) for k, v in row.items()
                            })
            if not out:
                raise ValueError(f"no batches found in {self.paths}")
            self._batches = out
        return self._batches

    def next(self) -> Dict[str, np.ndarray]:
        batches = self._load()
        i = (int(self.rng.integers(len(batches)))
             if self.shuffle else getattr(self, "_i", 0) % len(batches))
        if not self.shuffle:
            self._i = i + 1
        return batches[i]

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        for b in self._load():
            yield b

    def read_all(self) -> Dict[str, np.ndarray]:
        """Concatenate every batch into one flat table."""
        batches = self._load()
        return {
            k: np.concatenate([np.atleast_1d(b[k]) for b in batches])
            for k in batches[0]
        }


def collect_offline_dataset(env_spec, path: str, *, num_episodes: int = 50,
                            policy=None, seed: int = 0,
                            epsilon: float = 0.3) -> int:
    """Roll episodes with a (possibly epsilon-soft) behavior policy and
    write per-episode batches with action probabilities — the columns the
    IS/WIS estimators need (reference: offline data includes
    action_prob/action_logp).  Returns total steps written."""
    from .env import make_env

    env = make_env(env_spec, seed=seed)
    rng = np.random.default_rng(seed)
    writer = JsonWriter(path)
    total = 0
    for ep in range(num_episodes):
        obs = env.reset(seed=seed * 10_000 + ep)
        rows: Dict[str, List] = {"obs": [], "actions": [], "rewards": [],
                                 "action_prob": [], "dones": []}
        while True:
            if policy is None:
                a = int(rng.integers(env.num_actions))
                prob = 1.0 / env.num_actions
            else:
                greedy_a, greedy_p = policy(obs)
                if rng.random() >= epsilon:
                    a = greedy_a
                else:
                    a = int(rng.integers(env.num_actions))
                # Behavior prob of the ACTION TAKEN under the epsilon-soft
                # mixture: the policy's mass on a (its reported prob when a
                # is its own choice, 0 otherwise — the protocol's policies
                # are deterministic-per-obs) plus the uniform explore mass.
                p_pol = greedy_p if a == greedy_a else 0.0
                prob = (1 - epsilon) * p_pol + epsilon / env.num_actions
            nxt, r, term, trunc = env.step(a)
            rows["obs"].append(np.asarray(obs).tolist())
            rows["actions"].append(int(a))
            rows["rewards"].append(float(r))
            rows["action_prob"].append(float(prob))
            rows["dones"].append(bool(term or trunc))
            total += 1
            obs = nxt
            if term or trunc:
                break
        writer.write({k: np.asarray(v) for k, v in rows.items()})
    writer.close()
    return total


class BC:
    """Behavior cloning: supervised learning of the dataset's action
    distribution (reference: algorithms/bc/bc.py — the BC loss is plain
    -logp on offline batches, sharing the learner stack).  Reuses the PPO
    model catalog, so MLP or CNN policies clone equally."""

    def __init__(self, obs_shape, num_actions: int, *, lr: float = 1e-3,
                 hidden: int = 64, seed: int = 0, model=None):
        import jax
        import optax

        from .models import default_model

        self.model = model or default_model(tuple(obs_shape), num_actions,
                                            hidden)
        self.params = self.model.init(seed)
        self.tx = optax.adam(lr)
        self.opt_state = self.tx.init(self.params)
        mdl, tx = self.model, self.tx

        def update(params, opt_state, obs, actions):
            def loss_fn(p):
                import jax.numpy as jnp

                logits, _ = mdl.apply(p, obs)
                logp = jax.nn.log_softmax(logits)
                return -jnp.take_along_axis(
                    logp, actions[:, None], axis=1).mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax as _ox

            return _ox.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)

    def train_on(self, reader: JsonReader, *, num_steps: int = 200,
                 batch_size: int = 256, seed: int = 0) -> float:
        import jax.numpy as jnp

        table = reader.read_all()
        obs = np.asarray(table["obs"], np.float32)
        actions = np.asarray(table["actions"], np.int32)
        rng = np.random.default_rng(seed)
        loss = float("nan")
        for _ in range(num_steps):
            idx = rng.integers(0, len(actions), batch_size)
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, jnp.asarray(obs[idx]),
                jnp.asarray(actions[idx]))
        return float(loss)

    def compute_action(self, obs: np.ndarray) -> int:
        logits, _ = self.model.apply(self.params, np.asarray(obs)[None])
        return int(np.argmax(np.asarray(logits)[0]))


def importance_sampling_estimate(
    reader: JsonReader, target_action_probs, *, gamma: float = 0.99,
    weighted: bool = False,
) -> Dict[str, float]:
    """Off-policy value estimation for a target policy from behavior data.

    target_action_probs(obs [T, D], actions [T]) -> [T] probabilities under
    the TARGET policy.  Ordinary IS multiplies per-step ratios over the
    episode and weights its discounted return; WIS normalizes by the mean
    cumulative ratio, trading bias for variance (reference:
    estimators/importance_sampling.py:21, weighted_importance_sampling.py).
    """
    v_behavior: List[float] = []
    v_target: List[float] = []
    weights: List[float] = []
    for ep in reader:
        rewards = np.asarray(ep["rewards"], np.float64)
        probs_b = np.asarray(ep["action_prob"], np.float64)
        probs_t = np.asarray(
            target_action_probs(np.asarray(ep["obs"], np.float32),
                                np.asarray(ep["actions"], np.int32)),
            np.float64)
        t = len(rewards)
        disc = gamma ** np.arange(t)
        ret = float((rewards * disc).sum())
        rho = float(np.prod(probs_t / np.clip(probs_b, 1e-8, None)))
        v_behavior.append(ret)
        v_target.append(rho * ret)
        weights.append(rho)
    if weighted:
        denom = max(float(np.mean(weights)), 1e-8)
        v_est = float(np.mean(v_target)) / denom
    else:
        v_est = float(np.mean(v_target))
    return {
        "v_behavior": float(np.mean(v_behavior)),
        "v_target": v_est,
        "mean_is_weight": float(np.mean(weights)),
        "episodes": len(v_behavior),
    }
