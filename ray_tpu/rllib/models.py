"""Policy model catalog: pluggable network architectures for the learners.

Role-equivalent to the reference's RLModule / model catalog layer
(reference: rllib/core/rl_module/rl_module.py, rllib/models/catalog.py —
the algorithm is architecture-agnostic; obs space picks the default net,
conv nets for image observations per models/utils.py get_filter_config).

A model is an object with:
    init(seed) -> params (a JAX pytree)
    apply(params, obs) -> (logits, value)
Learners and env runners only touch this surface, so MLP vs CNN (or a
custom user model) is a config swap, not a learner change.  TPU notes: the
CNN keeps channel counts in MXU-friendly multiples and uses NHWC layouts
(XLA's preferred TPU conv layout); everything jits into one program.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class MLPModel:
    """Separate-torso tanh MLP — the classic-control default (same
    architecture the PPO/IMPALA learners always used; reference: rllib
    default fcnet with vf_share_layers=False)."""

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 hidden: int = 64):
        self.obs_shape = tuple(obs_shape)
        self.obs_size = int(np.prod(obs_shape))
        self.num_actions = num_actions
        self.hidden = hidden

    def init(self, seed: int = 0):
        from .learner import init_policy

        return init_policy(self.obs_size, self.num_actions, self.hidden,
                           seed)

    def apply(self, params, obs):
        from .learner import policy_forward

        if obs.ndim > 2:
            obs = obs.reshape(obs.shape[0], -1)
        return policy_forward(params, obs)


class CNNModel:
    """Conv torso + dense policy/value heads for image observations
    (reference: rllib models/utils.py get_filter_config — conv stacks are
    the default for 2D/3D obs; benchmark_atari_ppo.py trains them at scale).

    NHWC activations, HWIO kernels — the layouts XLA maps best onto the TPU
    MXU's convolution path; channel counts default to multiples of 8 so the
    contraction dims tile cleanly."""

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 channels: Sequence[int] = (16, 32),
                 kernels: Sequence[int] = (3, 3),
                 strides: Sequence[int] = (1, 1),
                 dense: int = 128):
        if len(obs_shape) == 2:
            obs_shape = (*obs_shape, 1)  # H,W -> H,W,1
        assert len(obs_shape) == 3, f"CNNModel wants (H, W, C), got {obs_shape}"
        assert len(channels) == len(kernels) == len(strides)
        self.obs_shape = tuple(obs_shape)
        self.num_actions = num_actions
        self.channels = tuple(channels)
        self.kernels = tuple(kernels)
        self.strides = tuple(strides)
        self.dense = dense

    def _conv_out_hw(self) -> Tuple[int, int]:
        h, w, _ = self.obs_shape
        for k, s in zip(self.kernels, self.strides):
            h = -(-(h - k + 1) // s)  # VALID conv then ceil-div stride
            w = -(-(w - k + 1) // s)
        assert h > 0 and w > 0, "conv stack consumed the whole image"
        return h, w

    def init(self, seed: int = 0) -> Dict[str, Any]:
        n_layers = len(self.channels) + 3
        keys = jax.random.split(jax.random.PRNGKey(seed), n_layers)
        he = jax.nn.initializers.he_normal()
        params: Dict[str, Any] = {}
        c_in = self.obs_shape[-1]
        for i, (c_out, k) in enumerate(zip(self.channels, self.kernels)):
            params[f"conv{i}_w"] = he(keys[i], (k, k, c_in, c_out),
                                      jnp.float32)
            params[f"conv{i}_b"] = jnp.zeros(c_out)
            c_in = c_out
        h, w = self._conv_out_hw()
        flat = h * w * c_in
        params["dense_w"] = he(keys[-3], (flat, self.dense), jnp.float32)
        params["dense_b"] = jnp.zeros(self.dense)
        params["pi_w"] = jax.nn.initializers.orthogonal(0.01)(
            keys[-2], (self.dense, self.num_actions), jnp.float32)
        params["pi_b"] = jnp.zeros(self.num_actions)
        params["v_w"] = jax.nn.initializers.orthogonal(1.0)(
            keys[-1], (self.dense, 1), jnp.float32)
        params["v_b"] = jnp.zeros(1)
        return params

    def apply(self, params, obs):
        x = jnp.asarray(obs, jnp.float32)
        if x.ndim == 3:  # missing channel dim: B,H,W -> B,H,W,1
            x = x[..., None]
        for i, s in enumerate(self.strides):
            x = jax.lax.conv_general_dilated(
                x, params[f"conv{i}_w"], window_strides=(s, s),
                padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ) + params[f"conv{i}_b"]
            x = jax.nn.relu(x)
        x = x.reshape(x.shape[0], -1)
        h = jax.nn.relu(x @ params["dense_w"] + params["dense_b"])
        logits = h @ params["pi_w"] + params["pi_b"]
        value = (h @ params["v_w"] + params["v_b"])[..., 0]
        return logits, value


def default_model(obs_shape: Tuple[int, ...], num_actions: int,
                  hidden: int = 64):
    """Obs-shape dispatch (reference: catalog.py _get_encoder_config —
    1D obs -> MLP, 2D/3D obs -> conv stack)."""
    if len(obs_shape) >= 2:
        return CNNModel(obs_shape, num_actions)
    return MLPModel(obs_shape, num_actions, hidden)
