"""ray_tpu.rllib: reinforcement learning on the actor runtime.

Role-equivalent to RLlib's new API stack (reference: rllib/ — EnvRunner
actors sample vectorized envs, a Learner updates the policy, weights sync
through the object store), TPU-first: the learner is pure JAX (jit, or pjit
over a Mesh for multi-chip) and env runners are CPU actors.
"""

from .dqn import DQN, DQNConfig, DQNLearner
from .env import CartPoleEnv, VectorEnv, make_env, register_env
from .env_runner import EnvRunner
from .impala import Impala, ImpalaConfig, ImpalaEnvRunner, ImpalaLearner
from .learner import PPOLearner, compute_gae, init_policy, policy_forward
from .ppo import PPO, PPOConfig
from .replay import ReplayBuffer

__all__ = [
    "PPO", "PPOConfig", "PPOLearner", "EnvRunner",
    "Impala", "ImpalaConfig", "ImpalaEnvRunner", "ImpalaLearner",
    "DQN", "DQNConfig", "DQNLearner", "ReplayBuffer",
    "CartPoleEnv", "VectorEnv", "make_env", "register_env",
    "compute_gae", "init_policy", "policy_forward",
]
