"""ray_tpu.rllib: reinforcement learning on the actor runtime.

Role-equivalent to RLlib's new API stack (reference: rllib/ — EnvRunner
actors sample vectorized envs, a Learner updates the policy, weights sync
through the object store), TPU-first: the learner is pure JAX (jit, or pjit
over a Mesh for multi-chip) and env runners are CPU actors.
"""

from .dqn import DQN, DQNConfig, DQNLearner
from .env import CartPoleEnv, CatchEnv, VectorEnv, make_env, register_env
from .env_runner import EnvRunner
from .impala import Impala, ImpalaConfig, ImpalaEnvRunner, ImpalaLearner
from .learner import PPOLearner, compute_gae, init_policy, policy_forward
from .models import CNNModel, MLPModel, default_model
from .offline import (
    BC, JsonReader, JsonWriter, collect_offline_dataset,
    importance_sampling_estimate,
)
from .multi_agent import (
    MultiAgentCartPole, MultiAgentEnv, MultiAgentEnvRunner, MultiAgentPPO,
    MultiAgentPPOConfig,
)
from .ppo import PPO, PPOConfig
from .replay import ReplayBuffer
from .sac import SAC, ContinuousEnvRunner, PendulumEnv, SACConfig, SACLearner

__all__ = [
    "PPO", "PPOConfig", "PPOLearner", "EnvRunner",
    "Impala", "ImpalaConfig", "ImpalaEnvRunner", "ImpalaLearner",
    "DQN", "DQNConfig", "DQNLearner", "ReplayBuffer",
    "SAC", "SACConfig", "SACLearner", "ContinuousEnvRunner", "PendulumEnv",
    "MultiAgentEnv", "MultiAgentCartPole", "MultiAgentEnvRunner",
    "MultiAgentPPO", "MultiAgentPPOConfig",
    "CNNModel", "MLPModel", "default_model",
    "BC", "JsonReader", "JsonWriter", "collect_offline_dataset",
    "importance_sampling_estimate",
    "CartPoleEnv", "CatchEnv", "VectorEnv", "make_env", "register_env",
    "compute_gae", "init_policy", "policy_forward",
]
