"""Native fast-path loader.

Compiles ``fastpath.c`` on first import (cc -O3, a one-time ~1s cost, cached
next to the source keyed on source mtime) and exposes:

    copy(dest, src, nthreads=0) -> int     parallel memcpy, GIL released
    prefault(dest, nthreads=0) -> int      fault in backing pages
    available: bool                        False => pure-Python fallback

The build is best-effort: any toolchain failure degrades to a pure-Python
``copy`` (memoryview slice assignment) so the framework never hard-depends
on a compiler at runtime.  The reference keeps this entire path in C++
(reference: src/ray/object_manager/plasma/; python binds via Cython
python/ray/_raylet.pyx) — here only the memcpy/prefault inner loop is
native and the protocol logic stays in Python.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastpath.c")

available = False
_ext = None
_build_lock = threading.Lock()


def _so_path() -> str:
    tag = f"{sys.implementation.cache_tag}-{os.uname().machine}"
    san = os.environ.get("RT_NATIVE_SANITIZE")
    if san:
        tag += f"-{san}"  # never let a sanitized build shadow the normal one
    return os.path.join(_HERE, f"_fastpath.{tag}.so")


def _fresh(so: str) -> bool:
    try:
        return os.path.getmtime(so) >= os.path.getmtime(_SRC)
    except OSError:
        return False


def _build(so: str) -> bool:
    cc = os.environ.get("CC", "cc")
    inc = sysconfig.get_path("include")
    tmp = f"{so}.build-{os.getpid()}.so"
    cmd = [cc, "-O3", "-shared", "-fPIC", "-pthread", f"-I{inc}", _SRC, "-o", tmp]
    # Sanitized builds for the native data plane (the role of the
    # reference's bazel tsan/asan configs gating its C++ runtime —
    # .bazelrc build:tsan/build:asan): RT_NATIVE_SANITIZE=thread|address
    # rebuilds the extension instrumented; run python with
    # LD_PRELOAD=$(cc -print-file-name=lib<san>.so) so the sanitizer
    # runtime is present at dlopen (otherwise import falls back to pure
    # Python).  E.g.:
    #   rm ray_tpu/_native/_fastpath.*.so
    #   LD_PRELOAD=$(cc -print-file-name=libtsan.so) \
    #     RT_NATIVE_SANITIZE=thread python -m pytest tests/test_core_units.py
    san = os.environ.get("RT_NATIVE_SANITIZE")
    if san in ("thread", "address", "undefined"):
        cmd.insert(1, f"-fsanitize={san}")
        cmd.insert(1, "-g")
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
        if proc.returncode != 0:
            return False
        os.replace(tmp, so)  # atomic: concurrent builders race benignly
        return True
    except Exception:
        return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


# Every symbol the current protocol needs.  A cached .so missing any of
# these is a stale build: mixing (say) a native atomic wait_seq with a
# Python plain-store store_seq silently reintroduces the data race the
# atomic pair exists to prevent, so stale builds are rebuilt, never
# partially patched.
_REQUIRED = ("copy", "prefault", "wait_seq", "store_seq")


def _import_so(so: str):
    try:
        spec = importlib.util.spec_from_file_location(
            "ray_tpu._native._fastpath", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception:
        return None
    if any(not hasattr(mod, sym) for sym in _REQUIRED):
        return None  # stale ABI — caller rebuilds
    return mod


def _load():
    global _ext, available
    so = _so_path()
    with _build_lock:
        if _ext is not None:
            return
        mod = _import_so(so) if _fresh(so) else None
        if mod is None:
            # Missing, out of date, or symbol-incomplete: rebuild from
            # source.  (dlopen caches by path per-process, so the rebuild
            # helps the NEXT process if this one already dlopened a stale
            # image — that process stays on the pure-Python fallback, which
            # is slow but protocol-consistent on both sides of the pair.)
            if not _build(so):
                return
            mod = _import_so(so)
            if mod is None:
                return
        _ext = mod
        available = True


_load()

if available:
    copy = _ext.copy
    prefault = _ext.prefault
    wait_seq = _ext.wait_seq
    store_seq = _ext.store_seq
else:
    def copy(dest, src, nthreads: int = 0) -> int:  # type: ignore[misc]
        m = memoryview(src)
        if m.format != "B":
            m = m.cast("B")
        d = memoryview(dest)
        if d.format != "B":
            d = d.cast("B")
        d[: m.nbytes] = m
        return m.nbytes

    def prefault(dest, nthreads: int = 0) -> int:  # type: ignore[misc]
        return 0

    def store_seq(buf, offset: int, value: int) -> None:  # type: ignore[misc]
        import struct

        struct.pack_into("<Q", buf, offset, value)

    def wait_seq(buf, timeout_s: float, want_unread: int) -> bool:  # type: ignore[misc]
        import struct
        import time

        deadline = time.monotonic() + timeout_s
        mv = memoryview(buf)
        while True:
            w, r = struct.unpack_from("<QQ", mv, 0)
            if (w > r) == bool(want_unread):
                return True
            if time.monotonic() > deadline:
                return False
            time.sleep(0.0002)
