/* _fastpath: native data-plane primitives for the host object store.
 *
 * The object plane's hot path is memcpy into /dev/shm segments (put, pull,
 * spill).  CPython does that copy single-threaded while holding the GIL
 * (memoryview slice assignment), which caps large puts at a few GiB/s and
 * stalls every other thread in the process.  This module provides:
 *
 *   copy(dest, src, nthreads=0)  -- parallel memcpy, GIL released
 *   prefault(dest, nthreads=0)   -- touch pages in parallel (first-touch
 *                                   faults on fresh shm dominate cold puts)
 *
 * Role-equivalent to the memcpy/population work plasma does natively in the
 * reference (reference: src/ray/object_manager/plasma/store.cc writes into
 * dlmalloc'd shm from C++, never through the interpreter).
 *
 * Plain C + pthreads; no dependencies beyond the CPython C API.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <string.h>
#include <stdint.h>
#include <time.h>
#include <unistd.h>

typedef struct {
    char *dest;
    const char *src;   /* NULL for prefault */
    size_t n;
} span_t;

static void *copy_worker(void *arg) {
    span_t *s = (span_t *)arg;
    if (s->src != NULL) {
        memcpy(s->dest, s->src, s->n);
    } else {
        /* Touch one byte per page; write so the kernel allocates backing
         * pages for shm (read faults map the shared zero page). */
        volatile char *p = (volatile char *)s->dest;
        for (size_t off = 0; off < s->n; off += 4096)
            p[off] = p[off];
        if (s->n)
            p[s->n - 1] = p[s->n - 1];
    }
    return NULL;
}

/* Split [0, n) into k contiguous spans aligned to 64-byte cache lines and
 * run copy_worker over them on k threads (caller's thread runs span 0). */
static int run_spans(char *dest, const char *src, size_t n, int k) {
    if (k <= 1 || n < (size_t)k * 4096) {
        span_t s = {dest, src, n};
        copy_worker(&s);
        return 0;
    }
    pthread_t tids[64];
    span_t spans[64];
    if (k > 64) k = 64;
    /* Ceil-divide then align up so k spans always cover all n bytes
     * (floor-divide drops the tail whenever n/k is already aligned). */
    size_t chunk = ((n + (size_t)k - 1) / (size_t)k + 63) & ~(size_t)63;
    int started = 0;
    size_t off = 0;
    for (int i = 0; i < k && off < n; i++) {
        size_t len = chunk < n - off ? chunk : n - off;
        spans[i].dest = dest + off;
        spans[i].src = src ? src + off : NULL;
        spans[i].n = len;
        off += len;
        if (i > 0) {
            /* Record only successfully-created handles; a failed create
             * runs the span inline instead. */
            if (pthread_create(&tids[started], NULL, copy_worker,
                               &spans[i]) != 0) {
                copy_worker(&spans[i]);
                continue;
            }
            started++;
        }
    }
    copy_worker(&spans[0]);
    for (int i = 0; i < started; i++)
        pthread_join(tids[i], NULL);
    return 0;
}

static int default_threads(size_t n) {
    if (n < (8u << 20))
        return 1;
    long ncpu = sysconf(_SC_NPROCESSORS_ONLN);
    if (ncpu < 1) ncpu = 1;
    int k = (int)(n / (8u << 20));       /* >= 8 MiB per thread */
    if (k > ncpu) k = (int)ncpu;
    if (k > 16) k = 16;
    if (k < 1) k = 1;
    return k;
}

static PyObject *py_copy(PyObject *self, PyObject *args) {
    PyObject *dest_obj, *src_obj;
    int nthreads = 0;
    if (!PyArg_ParseTuple(args, "OO|i", &dest_obj, &src_obj, &nthreads))
        return NULL;
    Py_buffer dest, src;
    if (PyObject_GetBuffer(dest_obj, &dest, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (PyObject_GetBuffer(src_obj, &src, PyBUF_C_CONTIGUOUS) < 0) {
        PyBuffer_Release(&dest);
        return NULL;
    }
    if (src.len > dest.len) {
        PyBuffer_Release(&src);
        PyBuffer_Release(&dest);
        PyErr_Format(PyExc_ValueError,
                     "source (%zd bytes) larger than destination (%zd bytes)",
                     src.len, dest.len);
        return NULL;
    }
    size_t n = (size_t)src.len;
    int k = nthreads > 0 ? nthreads : default_threads(n);
    Py_BEGIN_ALLOW_THREADS
    run_spans((char *)dest.buf, (const char *)src.buf, n, k);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&src);
    PyBuffer_Release(&dest);
    return PyLong_FromSize_t(n);
}

static PyObject *py_prefault(PyObject *self, PyObject *args) {
    PyObject *dest_obj;
    int nthreads = 0;
    if (!PyArg_ParseTuple(args, "O|i", &dest_obj, &nthreads))
        return NULL;
    Py_buffer dest;
    if (PyObject_GetBuffer(dest_obj, &dest, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    size_t n = (size_t)dest.len;
    int k = nthreads > 0 ? nthreads : default_threads(n);
    Py_BEGIN_ALLOW_THREADS
    run_spans((char *)dest.buf, NULL, n, k);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&dest);
    return PyLong_FromSize_t(n);
}

/* Spin-then-sleep wait on an SPSC channel header: [u64 write_seq]
 * [u64 read_seq] at the buffer head.  want_unread=1 waits for
 * write_seq > read_seq (reader side); 0 waits for write_seq <= read_seq
 * (writer side, slot free).  GIL released; acquire loads pair with the
 * peer process's stores through the coherent shm mapping.  Python-level
 * spin loops cost ~1us/iteration in interpreter overhead; this loop is
 * ~1ns/iteration, which is what makes sub-100us DAG hops possible. */
static PyObject *py_wait_seq(PyObject *self, PyObject *args) {
    PyObject *buf_obj;
    double timeout_s;
    int want_unread;
    if (!PyArg_ParseTuple(args, "Odi", &buf_obj, &timeout_s, &want_unread))
        return NULL;
    Py_buffer buf;
    if (PyObject_GetBuffer(buf_obj, &buf, PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (buf.len < 16) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "buffer too small for seq header");
        return NULL;
    }
    const uint64_t *w = (const uint64_t *)buf.buf;
    const uint64_t *r = w + 1;
    int ok = 0;
    Py_BEGIN_ALLOW_THREADS
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    double deadline = ts.tv_sec + ts.tv_nsec * 1e-9 + timeout_s;
    long spins = 0;
    for (;;) {
        uint64_t wv = __atomic_load_n(w, __ATOMIC_ACQUIRE);
        uint64_t rv = __atomic_load_n(r, __ATOMIC_ACQUIRE);
        int unread = wv > rv;
        if (unread == (want_unread != 0)) { ok = 1; break; }
        if (++spins < 20000) {
#if defined(__x86_64__) || defined(__i386__)
            __builtin_ia32_pause();
#endif
            continue;
        }
        clock_gettime(CLOCK_MONOTONIC, &ts);
        if (ts.tv_sec + ts.tv_nsec * 1e-9 > deadline) break;
        struct timespec nap = {0, 50000};  /* 50us */
        nanosleep(&nap, NULL);
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&buf);
    if (ok) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

/* Atomic release store of a u64 header word.  Pairs with wait_seq's acquire
 * loads: on x86_64/aarch64 a plain aligned store happens to be atomic, but
 * mixing plain stores with atomic loads is UB-adjacent and can tear on other
 * architectures — all header publishes go through here instead. */
static PyObject *py_store_seq(PyObject *self, PyObject *args) {
    PyObject *buf_obj;
    Py_ssize_t offset;
    unsigned long long value;
    if (!PyArg_ParseTuple(args, "OnK", &buf_obj, &offset, &value))
        return NULL;
    Py_buffer buf;
    if (PyObject_GetBuffer(buf_obj, &buf, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) < 0)
        return NULL;
    if (offset < 0 || offset + 8 > buf.len || (offset & 7) != 0) {
        PyBuffer_Release(&buf);
        PyErr_SetString(PyExc_ValueError, "offset must be 8-aligned and in range");
        return NULL;
    }
    uint64_t *p = (uint64_t *)((char *)buf.buf + offset);
    __atomic_store_n(p, (uint64_t)value, __ATOMIC_RELEASE);
    PyBuffer_Release(&buf);
    Py_RETURN_NONE;
}

static PyMethodDef methods[] = {
    {"copy", py_copy, METH_VARARGS,
     "copy(dest, src, nthreads=0) -> bytes copied.  Parallel memcpy with the "
     "GIL released; nthreads=0 picks a size-based default."},
    {"prefault", py_prefault, METH_VARARGS,
     "prefault(dest, nthreads=0) -> bytes touched.  Fault in backing pages."},
    {"wait_seq", py_wait_seq, METH_VARARGS,
     "wait_seq(buf, timeout_s, want_unread) -> bool.  Spin-then-sleep wait "
     "on an SPSC [write_seq, read_seq] header; True when satisfied, False "
     "on timeout."},
    {"store_seq", py_store_seq, METH_VARARGS,
     "store_seq(buf, offset, value).  Atomic release store of a u64 header "
     "word (pairs with wait_seq's acquire loads)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_fastpath",
    "Native data-plane primitives (parallel memcpy / prefault).",
    -1, methods,
};

PyMODINIT_FUNC PyInit__fastpath(void) {
    return PyModule_Create(&moduledef);
}
