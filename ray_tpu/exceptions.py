"""Public exception types (role-equivalent of python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for framework errors."""


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get() with the remote
    traceback attached (reference: python/ray/exceptions.py RayTaskError)."""

    def __init__(self, cause: BaseException, remote_traceback: str = ""):
        super().__init__(
            f"task raised {type(cause).__name__}: {cause}\n"
            f"--- remote traceback ---\n{remote_traceback}"
        )
        self.cause = cause
        self.remote_traceback = remote_traceback

    def __reduce__(self):
        # Default Exception reduce would re-init with the formatted message
        # string as `cause`, double-wrapping on unpickle.
        return (type(self), (self.cause, self.remote_traceback))


class TaskCancelledError(RayTpuError):
    pass


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id_hex: str, cause: str = ""):
        super().__init__(f"actor {actor_id_hex[:12]} died: {cause}")
        self.actor_id_hex = actor_id_hex
        self.cause = cause

    def __reduce__(self):
        # Default Exception reduce would re-init with the formatted message
        # as actor_id_hex, garbling both attributes after crossing the wire.
        return (type(self), (self.actor_id_hex, self.cause))


class ObjectLostError(RayTpuError):
    pass


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage reconstruction was attempted for a lost object but failed
    (no lineage, retries exhausted, depth limit, or the re-executed task
    failed) — a subtype of ObjectLostError so callers handling loss
    generically keep working (reference: object_recovery_manager.h:90)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class DeadlineExceededError(RayTpuError, TimeoutError):
    """A call's per-call deadline budget (core/deadline.py) ran out before
    any route — peer or head — produced a result.  Distinct from
    GetTimeoutError: the CALL is abandoned (and its result sealed with
    this error), not just one blocking get() giving up."""


from .core.rpc import ConnectionLost as _ConnectionLost


class HeadRestartedError(RayTpuError, _ConnectionLost):
    """A non-idempotent control-plane call was interrupted by a lost head
    connection — the head may have crashed/restarted mid-call, so the
    framework cannot know whether the operation landed.  Carries the method
    (and an optional detail) so the caller can decide to resubmit
    (reference: GCS FT — non-retryable RPCs surface to the caller on a GCS
    failover instead of being silently replayed).  Subclasses
    ``core.rpc.ConnectionLost`` so existing connection-error handling keeps
    working; catch this type specifically to implement resubmission.
    """

    def __init__(self, method: str, detail: str = ""):
        msg = (
            f"head connection lost during non-idempotent call {method!r}; "
            "the head may have restarted — idempotent state was preserved "
            "by the durable snapshot, but this operation must be "
            "resubmitted by the caller"
        )
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.method = method
        self.detail = detail

    def __reduce__(self):
        # Default Exception reduce would re-init with the formatted message
        # as `method`, garbling both attributes after crossing the wire.
        return (type(self), (self.method, self.detail))


class RuntimeEnvSetupError(RayTpuError):
    pass
