"""Operator CLI: `python -m ray_tpu <command>`.

Role-equivalent to the reference's `ray` CLI + state API commands
(reference: python/ray/scripts/scripts.py:76, util/state/api.py:781 `ray
list ...`, `ray summary`, `ray timeline`, `ray status`): inspects a running
cluster over the control-plane RPC.  The address comes from --address,
RT_ADDRESS, or /tmp/ray_tpu/latest_address (written by init()).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _resolve_address(addr: Optional[str]) -> str:
    if addr:
        return addr
    if os.environ.get("RT_ADDRESS"):
        return os.environ["RT_ADDRESS"]
    try:
        with open("/tmp/ray_tpu/latest_address") as f:
            return f.read().strip()
    except OSError:
        raise SystemExit(
            "no cluster address (use --address, RT_ADDRESS, or start a "
            "cluster first)"
        )


def _client(addr: Optional[str]):
    from .core.client import Client

    return Client(_resolve_address(addr), kind="driver", pid=os.getpid())


def _format_table(rows, columns, empty: str = "(no items)") -> str:
    if not rows:
        return empty
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
        for c in columns
    }
    out = ["  ".join(c.upper().ljust(widths[c]) for c in columns)]
    for r in rows:
        out.append(
            "  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns))
    return "\n".join(out)


def _print_table(rows, columns, empty: str = "(no items)"):
    print(_format_table(rows, columns, empty))


def _union_columns(items) -> list:
    """Column set spanning EVERY row (first-seen order): heterogeneous
    state rows (e.g. pending vs reserved placement groups) must not have
    fields silently dropped because items[0] happened to lack them."""
    cols: list = []
    for r in items:
        for k in r:
            if k not in cols:
                cols.append(k)
    return cols


_LIST_COLUMNS = {
    "actors": ["actor_id", "class_name", "state", "name", "pid",
               "num_executed_tasks"],
    "tasks": ["task_id", "name", "state", "error"],
    "nodes": ["node_id", "alive", "resources", "available"],
    "workers": ["worker_id", "node_id", "state", "pid"],
    "objects": ["object_id", "size", "sealed", "inline", "ref_count"],
    "placement_groups": ["pg_id", "strategy", "created", "name"],
    "logs": ["proc_id", "kind", "node_id", "pid", "alive", "actor_id",
             "log_path"],
    "task_events": ["task_id", "name", "state", "node_id", "worker_id",
                    "error"],
    "incidents": ["id", "kind", "severity", "state", "fired_count",
                  "summary"],
    "gang_rounds": ["gang", "world", "last_t", "latest"],
}


def cmd_list(args) -> int:
    kind = {"pgs": "placement_groups"}.get(args.kind, args.kind)
    cl = _client(args.address)
    try:
        items = cl.call("list_state", {"kind": kind})["items"]
        if args.json:
            print(json.dumps(items, indent=1, default=str))
        else:
            _print_table(items, _LIST_COLUMNS.get(kind) or
                         _union_columns(items), empty=f"(no {kind})")
    finally:
        cl.close()
    return 0


def cmd_status(args) -> int:
    cl = _client(args.address)
    try:
        nodes = cl.call("list_state", {"kind": "nodes"})["items"]
        workers = cl.call("list_state", {"kind": "workers"})["items"]
        actors = cl.call("list_state", {"kind": "actors"})["items"]
        total = cl.call("cluster_resources")["resources"]
        avail = cl.call("available_resources")["resources"]
        health = _health_line(cl)
        if health:
            print(health)
        print(f"nodes: {sum(1 for n in nodes if n.get('alive'))} alive / "
              f"{len(nodes)}")
        print(f"workers: {len(workers)}  actors: "
              f"{sum(1 for a in actors if a['state'] == 'ALIVE')} alive")
        for res in sorted(total):
            used = total[res] - avail.get(res, 0)
            print(f"  {res}: {used:g}/{total[res]:g} used")
        stats = cl.call("store_stats")
        used_b = stats.get("used_bytes", 0)
        cap_b = stats.get("capacity_bytes", 0)
        print(f"object store: {used_b / 2**20:.1f}/{cap_b / 2**20:.1f} "
              "MiB used (head node)")
        # Head fault-tolerance posture: restarts survived, field resyncs
        # adopted, and per-node headless time (ray_tpu_headless_seconds).
        try:
            rows = cl.call("list_state", {"kind": "metrics"})["items"]
            restarts = sum(r.get("value", 0) for r in rows
                           if r["name"] == "ray_tpu_head_restarts_total")
            resyncs = sum(r.get("value", 0) for r in rows
                          if r["name"] == "ray_tpu_resync_reports_total")
            headless = [(r.get("tags", {}).get("node", "?")[:8],
                         r.get("value", 0.0)) for r in rows
                        if r["name"] == "ray_tpu_headless_seconds"]
            if restarts or resyncs or headless:
                print(f"head restarts: {restarts:g}  "
                      f"resync reports: {resyncs:g}")
                for node, secs in sorted(headless):
                    print(f"  node {node}: {secs:.1f}s headless")
        except Exception:
            pass  # older head without the FT metrics: stay quiet
        # Inference engines (flight-recorder + devmem planes): one line
        # per engine with batch occupancy, KV pages, adapter pins, and
        # device bytes by pool.
        try:
            engines = cl.call(
                "list_state", {"kind": "engine_steps", "limit": 64}
            )["items"]
            devmem = cl.call("list_state", {"kind": "devmem"})["items"]
            for row in _engine_rows(engines, devmem):
                print(f"  engine {row['engine']}: slots {row['slots']}  "
                      f"queued {row['queued']}  pages {row['pages']}  "
                      f"stall {row['stall%']}%  "
                      f"adapters pinned {row['adapters']}"
                      + (f"  hbm {row['hbm']}" if row["hbm"] else ""))
        except Exception:
            pass  # older head without the observability plane: stay quiet
    finally:
        cl.close()
    return 0


def _engine_rows(engines, devmem_items) -> list:
    """Join engine flight-recorder windows with devmem pool snapshots into
    display rows (shared by `status` and `top`).  Engine ids are
    ``<pid>.<seq>``, so the pid prefix keys into the devmem reports."""
    dm_by_pid = {d.get("pid"): (d.get("devmem") or {}) for d in devmem_items}
    rows = []
    for e in engines:
        recs = e.get("records") or []
        latest = e.get("latest") or {}
        wall = sum(float(r.get("wall_s") or 0) for r in recs)
        stall = sum(float(r.get("stall_s") or 0) for r in recs)
        try:
            pid = int(str(e.get("engine", "")).split(".", 1)[0])
        except ValueError:
            pid = None
        pools = dm_by_pid.get(pid, {}).get("pools") or {}
        tenants = latest.get("tenants") or {}
        rows.append({
            "engine": e.get("engine", "?"),
            "slots": f"{latest.get('occupancy', 0)}/"
                     f"{latest.get('slots', 0)}",
            "queued": latest.get("queued", 0),
            "stall%": f"{100.0 * stall / wall:.1f}" if wall > 0 else "0.0",
            "pages": f"{latest.get('pages_used', 0)}u/"
                     f"{latest.get('pages_free', 0)}f",
            "adapters": latest.get("adapter_pins", 0),
            "hbm": " ".join(
                f"{name}={nbytes / 2**20:.0f}M"
                for name, nbytes in sorted(pools.items()) if nbytes
            ),
            "tenants": " ".join(
                f"{t}:{n}" for t, n in sorted(tenants.items())) or "-",
        })
    return rows


def _gang_rows(items) -> list:
    """Display rows for the gang skew join (shared by `gang` and `top`):
    one line per gang with its latest joined round's wall/skew and
    straggler attribution."""
    rows = []
    for g in items:
        latest = g.get("latest") or {}
        skew = latest.get("skew_s")
        frac = latest.get("skew_frac")
        rows.append({
            "gang": g.get("gang", "?"),
            "world": g.get("world", 0),
            "round": latest.get("round", "-"),
            "wall": f"{latest.get('wall_s', 0):.3f}s" if latest else "-",
            "skew": f"{skew:.3f}s ({100 * frac:.0f}%)"
            if isinstance(skew, (int, float)) else "-",
            "straggler": f"r{latest.get('straggler')}:{latest.get('phase')}"
            if latest.get("straggler") is not None else "-",
            "data%": f"{100 * latest.get('data_frac', 0):.0f}"
            if latest else "-",
            "coll%": f"{100 * latest.get('coll_frac', 0):.0f}"
            if latest else "-",
            "mfu": f"{latest.get('mfu'):.3f}"
            if isinstance(latest.get("mfu"), (int, float)) else "-",
        })
    return rows


def cmd_gang(args) -> int:
    """Gang training skew: per-round straggler attribution joined from the
    per-rank round flight recorders.  Without an id, one summary line per
    gang; with an id (prefix), the recent skew profiles plus the newest
    raw record from every rank."""
    import time as _time

    cl = _client(args.address)
    try:
        body = {"kind": "gang_rounds", "limit": max(1, args.rounds)}
        if args.gang:
            body["gang"] = args.gang
        items = cl.call("list_state", body)["items"]
        if args.json:
            print(json.dumps(items, indent=1, default=str))
            return 0
        if not items:
            print(f"(no gang matching {args.gang!r})" if args.gang else
                  "(no gang rounds joined yet — flight recorder off or no "
                  "multi-rank train run)")
            return 1 if args.gang else 0
        if not args.gang:
            _print_table(_gang_rows(items),
                         ["gang", "world", "round", "wall", "skew",
                          "straggler", "data%", "coll%", "mfu"])
            return 0
        now = _time.time()
        for g in items:
            print(f"gang {g.get('gang')}  world {g.get('world')}  "
                  f"last seen {_age(now, g.get('last_t'))} ago")
            ranks = g.get("ranks") or {}
            rank_rows = [{
                "rank": r, "round": rec.get("round"),
                "wall": f"{rec.get('wall_s', 0):.3f}",
                "data": f"{rec.get('data_s', 0):.3f}",
                "coll": f"{rec.get('coll_s', 0):.3f}",
                "ckpt": f"{rec.get('ckpt_s', 0):.3f}",
                "ack": f"{rec.get('ack_s', 0):.3f}",
                "mfu": f"{rec.get('mfu'):.3f}"
                if isinstance(rec.get("mfu"), (int, float)) else "-",
            } for r, rec in sorted(ranks.items(), key=lambda kv: int(kv[0]))]
            _print_table(rank_rows,
                         ["rank", "round", "wall", "data", "coll", "ckpt",
                          "ack", "mfu"], empty="(no per-rank records)")
            prof_rows = [{
                "round": p.get("round"),
                "wall": f"{p.get('wall_s', 0):.3f}",
                "skew": f"{p.get('skew_s', 0):.3f}",
                "skew%": f"{100 * p.get('skew_frac', 0):.0f}",
                "straggler": f"r{p.get('straggler')}",
                "phase": p.get("phase"),
                "lag": f"{p.get('phase_lag_s', 0):.3f}",
                "mfu": f"{p.get('mfu'):.3f}"
                if isinstance(p.get("mfu"), (int, float)) else "-",
            } for p in (g.get("profiles") or [])]
            print()
            _print_table(prof_rows,
                         ["round", "wall", "skew", "skew%", "straggler",
                          "phase", "lag", "mfu"],
                         empty="(no joined rounds yet)")
    finally:
        cl.close()
    return 0


def _node_row(n: dict) -> dict:
    stats = n.get("stats") or {}
    mem = stats.get("mem_used_frac")
    return {
        "node": n.get("node_id", "")[:8],
        "alive": n.get("alive"),
        "load1": stats.get("load1", ""),
        "mem%": round(100 * mem, 1) if isinstance(mem, (int, float)) else "",
        "procs": stats.get("num_worker_procs", ""),
        "cpu": "{:g}/{:g}".format(
            (n.get("available") or {}).get("CPU", 0),
            (n.get("resources") or {}).get("CPU", 0)),
    }


def _render_top(cl) -> str:
    """One frame of `ray_tpu top`: cluster header, node table, and the
    per-engine occupancy/stall/pages/HBM table."""
    import time as _time

    nodes = cl.call("list_state", {"kind": "nodes"})["items"]
    workers = cl.call("list_state", {"kind": "workers"})["items"]
    engines = cl.call(
        "list_state", {"kind": "engine_steps", "limit": 64})["items"]
    devmem = cl.call("list_state", {"kind": "devmem"})["items"]
    gangs = cl.call("list_state", {"kind": "gang_rounds", "limit": 1})["items"]
    alive = sum(1 for n in nodes if n.get("alive"))
    health = _health_line(cl)
    sections = [
        f"ray_tpu top  {_time.strftime('%H:%M:%S')}  "
        f"nodes {alive}/{len(nodes)} alive  workers {len(workers)}"
        + (f"  |  {health}" if health else ""),
        "",
        _format_table(
            [_node_row(n) for n in nodes],
            ["node", "alive", "load1", "mem%", "procs", "cpu"],
            empty="(no nodes)",
        ),
        "",
        _format_table(
            _engine_rows(engines, devmem),
            ["engine", "slots", "queued", "stall%", "pages", "adapters",
             "hbm", "tenants"],
            empty="(no engines reporting — flight recorder off or no "
                  "serve traffic yet)",
        ),
    ]
    if gangs:
        # Gang section only when a train gang is actually reporting —
        # serve-only clusters keep the frame compact.
        sections += ["", _format_table(
            _gang_rows(gangs),
            ["gang", "world", "round", "wall", "skew", "straggler",
             "data%", "coll%", "mfu"])]
    return "\n".join(sections)


def cmd_top(args) -> int:
    """Auto-refreshing cluster table (reference: `ray status -v` + the
    dashboard, as a terminal loop): nodes, workers, and per-engine
    occupancy/stall%/KV pages/HBM-by-pool from the flight-recorder and
    devmem planes.  --once renders a single frame (scripts/CI)."""
    cl = _client(args.address)
    try:
        while True:
            try:
                frame = _render_top(cl)
            except KeyboardInterrupt:
                return 0
            except Exception as e:
                frame = f"(top refresh failed: {e})"
            if not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(frame)
            sys.stdout.flush()
            if args.once:
                return 0
            try:
                import time as _time

                _time.sleep(max(0.2, args.interval))
            except KeyboardInterrupt:
                return 0
    finally:
        cl.close()


def cmd_down(args) -> int:
    """Shut the whole cluster down over the control plane (reference:
    `ray stop`): the head tears down workers, node daemons and itself."""
    cl = _client(args.address)
    try:
        cl.call("shutdown_cluster", {})
        print("cluster shutdown requested")
    finally:
        try:
            cl.close()
        except Exception:
            pass  # the head is going away under us by design
    return 0


def cmd_lint(args) -> int:
    """rtlint: framework-aware static analysis over the ray_tpu package
    (rules RT001-RT012; see ray_tpu/devtools/rtlint.py).  Needs no
    running cluster."""
    from .devtools import rtlint

    argv = []
    if args.json:
        argv.append("--json")
    if args.root:
        argv += ["--root", args.root]
    if args.allowlist:
        argv += ["--allowlist", args.allowlist]
    return rtlint.main(argv)


def cmd_summary(args) -> int:
    """Task summary by name+state (reference: `ray summary tasks`)."""
    cl = _client(args.address)
    try:
        items = cl.call("list_state", {"kind": "tasks"})["items"]
        agg = {}
        for t in items:
            key = (t.get("name", ""), t.get("state", ""))
            agg[key] = agg.get(key, 0) + 1
        rows = [
            {"name": k[0], "state": k[1], "count": v}
            for k, v in sorted(agg.items())
        ]
        _print_table(rows, ["name", "state", "count"])
    finally:
        cl.close()
    return 0


def cmd_metrics(args) -> int:
    cl = _client(args.address)
    try:
        rows = cl.call("list_state", {"kind": "metrics"})["items"]
        if args.prometheus:
            from .util.metrics import prometheus_text

            sys.stdout.write(prometheus_text(rows))
        else:
            _print_table(rows, ["name", "kind", "tags", "value"])
    finally:
        cl.close()
    return 0


def cmd_timeline(args) -> int:
    cl = _client(args.address)
    try:
        items = cl.call("list_state", {"kind": "timeline"})["items"]
        if getattr(args, "chrome", False):
            # chrome://tracing / Perfetto format from span events
            # (reference: `ray timeline` emits the same shape).
            from .util.tracing import chrome_trace

            print(json.dumps(chrome_trace(items)))
        else:
            print(json.dumps(items, indent=1, default=str))
    finally:
        cl.close()
    return 0


def cmd_trace(args) -> int:
    """Per-request trace analysis (the span-plane query surface): without
    an id, lists recent traces; with a trace id (hex prefix ok), prints
    the ASCII waterfall, the critical path, and the per-stage latency
    breakdown; ``--chrome`` exports that one trace as chrome://tracing
    JSON with the submit->execute flow arrows."""
    cl = _client(args.address)
    try:
        if not args.trace_id:
            items = cl.call("list_state", {"kind": "traces"})["items"]
            if args.json:
                print(json.dumps(items, indent=1, default=str))
            else:
                _print_table(
                    items,
                    ["trace_id", "root", "spans", "start", "duration_s"],
                    empty="(no traces)")
            return 0
        reply = cl.call(
            "list_state", {"kind": "traces", "trace_id": args.trace_id})
        spans = reply["items"]
        ambiguous = reply.get("ambiguous_matches")
        if ambiguous:
            print(
                f"note: prefix {args.trace_id!r} matches "
                f"{len(ambiguous)} traces — showing the most recent "
                f"({spans[0].get('trace_id', '?')}); others: "
                + " ".join(t[:16] for t in ambiguous[:8]),
                file=sys.stderr)
        if not spans:
            print(f"(no spans for trace {args.trace_id!r} — sampled out, "
                  "expired from the timeline ring, or wrong id)",
                  file=sys.stderr)
            return 1
        if getattr(args, "chrome", False):
            from .util.tracing import chrome_trace

            print(json.dumps(chrome_trace(spans)))
            return 0
        if args.json:
            print(json.dumps(spans, indent=1, default=str))
            return 0
        from .util import trace_analysis

        print(trace_analysis.format_trace(spans))
    finally:
        cl.close()
    return 0


def _health_line(cl) -> Optional[str]:
    """One-line cluster health grade for `status` and the `top` header;
    None against a head without the incident plane."""
    try:
        reply = cl.call("list_state", {"kind": "incidents"})
    except Exception:
        return None
    grade = reply.get("grade", "OK")
    n = reply.get("open", 0)
    line = f"health: {grade}  open incidents: {n}"
    if n:
        worst = next((i for i in reply.get("items", [])
                      if i.get("state") != "resolved"), None)
        if worst:
            line += f"  ({worst['kind']}: {worst['summary']})"
    return line


def _age(now: float, ts) -> str:
    if not isinstance(ts, (int, float)):
        return ""
    d = max(0.0, now - ts)
    return f"{d:.0f}s" if d < 120 else f"{d / 60:.0f}m"


def cmd_incidents(args) -> int:
    """Incident ring of the health plane: every detector firing that
    opened an incident, with lifecycle state and dedup counts."""
    import time as _time

    cl = _client(args.address)
    try:
        reply = cl.call("list_state", {"kind": "incidents"})
        items = reply["items"]
        if args.json:
            print(json.dumps(
                {"grade": reply.get("grade"), "open": reply.get("open"),
                 "incidents": items}, indent=1, default=str))
            return 0
        print(f"health: {reply.get('grade', 'OK')}  "
              f"open: {reply.get('open', 0)}  total: {len(items)}")
        now = _time.time()
        rows = [{
            "id": i.get("id"), "kind": i.get("kind"),
            "sev": i.get("severity"), "state": i.get("state"),
            "age": _age(now, i.get("opened")),
            "fired": i.get("fired_count"),
            "summary": str(i.get("summary", ""))[:72],
        } for i in items]
        _print_table(rows, ["id", "kind", "sev", "state", "age", "fired",
                            "summary"], empty="(no incidents)")
    finally:
        cl.close()
    return 0


def _doctor_object_plane(cl) -> int:
    """Put-path contention attribution from the cluster-aggregated stage
    histograms — where the object-plane put wall goes (the measurement
    gate for the zero-copy redesign, ROADMAP item 3)."""
    rows = cl.call("list_state", {"kind": "metrics"})["items"]

    def hist_rows(name):
        return [r for r in rows if r["name"] == name and "sum" in r]

    stages = {}
    for r in hist_rows("ray_tpu_put_copy_seconds"):
        stage = r.get("tags", {}).get("stage", "?")
        cur = stages.setdefault(stage, [0.0, 0])
        cur[0] += r.get("sum", 0.0)
        cur[1] += r.get("count", 0)
    lock = [(r.get("sum", 0.0), r.get("count", 0))
            for r in hist_rows("ray_tpu_store_lock_wait_seconds")]
    if lock:
        stages["lock_wait"] = [sum(s for s, _ in lock),
                               sum(c for _, c in lock)]
    outbox = [(r.get("sum", 0.0), r.get("count", 0))
              for r in hist_rows("ray_tpu_rpc_outbox_delay_seconds")]
    if not stages:
        print("(no put-stage samples yet — do a large put first)")
        return 1
    total = sum(s for s, _ in stages.values())
    print("object-plane put attribution (cluster cumulative):")
    table = [{
        "stage": stage, "seconds": f"{secs:.4f}", "ops": int(count),
        "share": f"{100 * secs / total:.1f}%" if total else "-",
    } for stage, (secs, count) in
        sorted(stages.items(), key=lambda kv: -kv[1][0])]
    _print_table(table, ["stage", "seconds", "ops", "share"])
    if outbox:
        osum = sum(s for s, _ in outbox)
        ocnt = sum(c for _, c in outbox)
        print(f"rpc outbox queue delay: {osum:.4f}s over {ocnt} "
              "drain bursts")
    return 0


def cmd_doctor(args) -> int:
    """Root-cause narrative for an incident: replays the evidence chain
    (trace links, task events, counter deltas) and runs the span-plane
    critical-path analysis on the slowest linked trace.  Without an id,
    diagnoses the most recent open incident; --object-plane prints the
    put-path contention attribution instead."""
    import time as _time

    cl = _client(args.address)
    try:
        if getattr(args, "object_plane", False):
            return _doctor_object_plane(cl)
        reply = cl.call("list_state", {"kind": "incidents"})
        items = reply["items"]
        if args.incident:
            items = [i for i in items
                     if str(i.get("id", "")).startswith(args.incident)]
            if not items:
                print(f"(no incident matching {args.incident!r})")
                return 1
        else:
            open_items = [i for i in items if i.get("state") != "resolved"]
            items = open_items or items
            if not items:
                print(f"health: {reply.get('grade', 'OK')} — no incidents "
                      "recorded; nothing to diagnose")
                return 0
        inc = items[0]
        now = _time.time()
        print(f"incident {inc['id']}  [{inc['kind']}/{inc['severity']}]  "
              f"state={inc['state']}")
        print(f"  {inc['summary']}")
        print(f"  opened {_age(now, inc.get('opened'))} ago, fired "
              f"{inc.get('fired_count', 1)}x, last "
              f"{_age(now, inc.get('last_fired'))} ago"
              + (f", resolved {_age(now, inc.get('resolved'))} ago"
                 if inc.get("resolved") else ""))
        ev = inc.get("evidence") or {}
        deltas = ev.get("counter_deltas") or (inc.get("data") or {}).get(
            "deltas")
        if deltas:
            print("  counter deltas in window: " + "  ".join(
                f"{k}=+{v:g}" for k, v in deltas.items()))
        if ev.get("step_window"):
            print("  step-record window: " + "  ".join(
                f"{k}={v}" for k, v in ev["step_window"].items()))
        if ev.get("gang"):
            # Gang incident: rank/phase attribution from the skew join.
            line = f"  gang {ev['gang']}"
            if ev.get("rank") is not None:
                line += f": straggler rank {ev['rank']}"
            if ev.get("phase"):
                line += f" late in {ev['phase']}"
            for k in ("skew_frac", "data_frac", "coll_frac"):
                if isinstance(ev.get(k), (int, float)):
                    line += f"  {k}={ev[k]:g}"
            print(line)
            for wr in (ev.get("worst_rounds") or [])[:3]:
                print("  worst round: " + "  ".join(
                    f"{k}={v}" for k, v in wr.items() if v is not None))
        for h in ev.get("slowest_handlers") or []:
            print(f"  handler {h['method']}: {h['total_s']}s "
                  f"over {h['calls']} calls")
        for e in (ev.get("task_events") or [])[:5]:
            print("  event: " + " ".join(
                f"{k}={v}" for k, v in e.items() if v is not None))
        tids = ev.get("trace_ids") or []
        if not tids:
            print("  (no linked traces in the evidence window)")
            return 0
        print(f"  linked traces: {len(tids)}")
        # Critical path of the slowest linked trace: the narrative's
        # "where the time actually went" section.
        slowest, slow_spans, slow_dur = None, None, -1.0
        for tid in tids:
            try:
                spans = cl.call("list_state",
                                {"kind": "traces", "trace_id": tid})["items"]
            except Exception:
                continue
            if not spans:
                continue
            starts = [s["start"] for s in spans
                      if isinstance(s.get("start"), (int, float))]
            ends = [s["end"] for s in spans
                    if isinstance(s.get("end"), (int, float))]
            dur = (max(ends) - min(starts)) if starts and ends else 0.0
            if dur > slow_dur:
                slowest, slow_spans, slow_dur = tid, spans, dur
        if slow_spans is None:
            print("  (linked traces already expired from the ring)")
            return 0
        from .util import trace_analysis

        print(f"\nslowest linked trace {str(slowest)[:16]} "
              f"({slow_dur:.3f}s):")
        print(trace_analysis.format_trace(slow_spans))
    finally:
        cl.close()
    return 0


def cmd_logs(args) -> int:
    """Cluster log retrieval (reference: `ray logs`).  Without an id, lists
    the head's log index — including EXITED processes, whose files stay
    retrievable for crash post-mortems.  With an id (worker/node hex
    prefix, actor id, or pid), streams that process's log; --follow keeps
    tailing a live process."""
    if getattr(args, "post_mortem", False):
        return _post_mortem_tails(args)
    cl = _client(args.address)
    try:
        if not args.id:
            items = cl.call("list_state", {"kind": "logs"})["items"]
            _print_table(items, _LIST_COLUMNS["logs"],
                         empty="(no registered logs)")
            return 0
        from .core.api import iter_log_chunks

        try:
            for data in iter_log_chunks(
                cl.call, args.id, offset=-args.tail if args.tail else 0,
                follow=args.follow,
            ):
                sys.stdout.write(data.decode("utf-8", "replace"))
                sys.stdout.flush()
        except RuntimeError as e:
            print(e, file=sys.stderr)
            return 1
    finally:
        cl.close()
    return 0


def _post_mortem_tails(args) -> int:
    """Dump the tail of every cluster process log — CI calls this when the
    test run fails so failures come with worker-side post-mortems.  Routes
    through the head's log index when a cluster is reachable; falls back
    to scanning the log root on the local filesystem."""
    import glob

    tail = args.tail or 4000
    paths: list = []
    try:
        cl = _client(args.address)
        try:
            paths = [e["log_path"] for e
                     in cl.call("list_state", {"kind": "logs"})["items"]
                     if e.get("log_path")]
        finally:
            cl.close()
    except (SystemExit, Exception):
        pass  # no live cluster: the filesystem fallback below still works
    if not paths:
        from .core.node_main import LOG_ROOT

        paths = sorted(
            glob.glob(os.path.join(LOG_ROOT, "*", "*.log")),
            key=lambda p: os.path.getmtime(p) if os.path.exists(p) else 0,
        )
    # Flight-recorder black boxes (<log>.steps.log sidecars) ride along
    # with their log's tail: the head's index stores only the log file
    # itself, and the SIGKILLed worker the sidecar exists for is exactly
    # the one a post-mortem is after.
    for path in list(paths):
        stem = path[:-4] if path.endswith(".log") else path
        sidecar = stem + ".steps.log"
        if sidecar != path and sidecar not in paths \
                and os.path.exists(sidecar):
            paths.append(sidecar)
    shown = 0
    for path in paths[-40:]:
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(max(0, size - tail))
                data = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        if not data.strip():
            continue
        print(f"==== {path} (last {min(size, tail)} bytes) ====")
        print(data)
        shown += 1
    if not shown:
        print("(no cluster process logs found)")
    return 0


def cmd_events(args) -> int:
    """Task lifecycle history (reference: `ray list tasks --detail` / the
    task events state API): per-task SUBMITTED/SCHEDULED/RUNNING/FINISHED/
    FAILED transitions with placement and failure tracebacks, retained at
    the head past worker/node death."""
    cl = _client(args.address)
    try:
        body = {"kind": "task_events"}
        if args.task:
            body["task_id"] = args.task
        if args.errors:
            body["errors"] = True
        items = cl.call("list_state", body)["items"]
        if args.json:
            print(json.dumps(items, indent=1, default=str))
            return 0
        if args.task:
            if not items:
                print(f"(no task events for {args.task!r})")
                return 0
            for rec in items:
                print(f"task {rec['task_id']}  name={rec.get('name', '')}  "
                      f"state={rec.get('state', '')}")
                for ev in rec.get("events", []):
                    where = " ".join(
                        f"{k}={ev[k]}" for k in ("node", "worker", "error")
                        if ev.get(k)
                    )
                    print(f"  {ev.get('ts', 0):.6f}  "
                          f"{ev.get('state', ''):<10} {where}")
                if rec.get("traceback"):
                    print("  traceback:")
                    for line in str(rec["traceback"]).splitlines():
                        print(f"    {line}")
            return 0
        rows = [
            {
                "task_id": r["task_id"][:16],
                "name": r.get("name", ""),
                "state": r.get("state", ""),
                "node_id": (r.get("node_id") or "")[:8],
                "worker_id": (r.get("worker_id") or "")[:8],
                "error": " ".join(str(r.get("error") or "").split())[:60],
            }
            for r in items
        ]
        _print_table(rows, _LIST_COLUMNS["task_events"],
                     empty="(no task events)")
    finally:
        cl.close()
    return 0


def cmd_stack(args) -> int:
    """On-demand all-thread stack dump of a live worker (reference:
    `ray stack`): the hung-gang diagnosis tool — collected by the worker's
    rpc thread without interrupting the running task."""
    cl = _client(args.address)
    try:
        reply = cl.call(
            "stack_dump",
            {"worker_id": args.worker_id, "timeout": args.timeout},
            timeout=args.timeout + 30,
        )
    finally:
        cl.close()
    if not reply.get("found") or not reply.get("ok"):
        print(reply.get("error", "stack dump failed"), file=sys.stderr)
        return 1
    print(f"worker {reply['worker_id'][:16]} pid={reply.get('pid')} "
          f"node={reply.get('node_id', '')[:8]} "
          f"threads={reply.get('threads')}")
    print(reply.get("dump", ""))
    return 0


def cmd_profile(args) -> int:
    """On-demand device-trace capture of a live worker (reference:
    `ray timeline`-class tooling; here the profiler of record is
    jax.profiler): the worker wraps its live process in
    util.profiling.device_trace for N seconds and replies with the
    TensorBoard trace dir."""
    cl = _client(args.address)
    try:
        body = {"worker_id": args.worker_id, "seconds": args.seconds}
        if args.logdir:
            body["logdir"] = args.logdir
        reply = cl.call("profile", body, timeout=args.seconds + 60)
    finally:
        cl.close()
    if not reply.get("found") or not reply.get("ok"):
        print(reply.get("error", "profile capture failed"), file=sys.stderr)
        return 1
    print(f"worker {reply['worker_id'][:16]} pid={reply.get('pid')} "
          f"node={reply.get('node_id', '')[:8]}")
    print(f"trace dir: {reply.get('logdir')}")
    print(f"view with: tensorboard --logdir {reply.get('logdir')}")
    return 0


def cmd_serve(args) -> int:
    """Declarative Serve operations (reference: `serve deploy/status/
    shutdown` CLI over the schema config)."""
    os.environ.setdefault("RT_ADDRESS", _resolve_address(args.address))
    from ray_tpu import serve as rt_serve

    if args.action == "deploy":
        if not args.config:
            raise SystemExit("serve deploy requires a config file path")
        handles = rt_serve.deploy_config(args.config)
        print(f"deployed {len(handles)} application(s)")
        st = rt_serve.status()
        for name, info in sorted(st.items()):
            print(f"  {name}: {info['running_replicas']}/"
                  f"{info['target_replicas']} replicas")
    elif args.action == "status":
        for name, info in sorted(rt_serve.status().items()):
            print(f"{name}: {info}")
    elif args.action == "shutdown":
        rt_serve.shutdown()
        print("serve shut down")
    return 0


def cmd_dashboard(args) -> int:
    """Serve the web dashboard against a running cluster (reference:
    dashboard/head.py runs as its own process attached to the GCS)."""
    from .dashboard import Dashboard

    dash = Dashboard(
        _resolve_address(args.address), host=args.host, port=args.port
    ).start()
    print(f"dashboard at {dash.url} (ctrl-c to stop)")
    try:
        import signal

        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        dash.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ray_tpu")
    ap.add_argument("--address", default=None)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("kind", choices=[
        "actors", "tasks", "nodes", "workers", "objects",
        "placement_groups", "pgs", "logs", "task_events",
        "engine_steps", "gang_rounds", "devmem", "incidents",
    ])
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser(
        "logs", help="cluster log index / per-process log retrieval"
    )
    p.add_argument("id", nargs="?", default=None,
                   help="worker/node id (hex prefix), actor id, or pid; "
                        "omit to list the index")
    p.add_argument("--follow", action="store_true",
                   help="keep tailing a live process")
    p.add_argument("--tail", type=int, default=0, metavar="BYTES",
                   help="start BYTES from the end of the log")
    p.add_argument("--post-mortem", action="store_true",
                   help="dump tails of every cluster process log "
                        "(index-routed, filesystem fallback) — for CI "
                        "failure forensics")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("events", help="task lifecycle event history")
    p.add_argument("--task", default=None,
                   help="show full transitions for tasks matching this id "
                        "prefix")
    p.add_argument("--errors", action="store_true",
                   help="only failed tasks")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser(
        "stack", help="dump all-thread Python stacks of a live worker"
    )
    p.add_argument("worker_id",
                   help="worker id (hex prefix) or actor id")
    p.add_argument("--timeout", type=float, default=15.0)
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("status", help="cluster resource summary")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser(
        "incidents",
        help="health-plane incident ring (detector firings + lifecycle)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_incidents)

    p = sub.add_parser(
        "doctor",
        help="root-cause narrative: replay an incident's evidence chain "
             "and critical-path the slowest linked trace")
    p.add_argument("incident", nargs="?", default=None,
                   help="incident id (prefix ok); omit for the most "
                        "recent open incident")
    p.add_argument("--object-plane", action="store_true",
                   help="print the put-path contention attribution "
                        "(stage split + store-lock wait + outbox delay)")
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "top", help="auto-refreshing cluster/engine table"
    )
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (scripts/CI)")
    p.set_defaults(fn=cmd_top)

    p = sub.add_parser(
        "gang",
        help="gang training skew: per-round straggler attribution from "
             "the rank flight recorders")
    p.add_argument("gang", nargs="?", default=None,
                   help="gang id (prefix ok) for the per-rank detail view; "
                        "omit for one summary line per gang")
    p.add_argument("--rounds", type=int, default=20,
                   help="joined skew profiles to show per gang")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_gang)

    p = sub.add_parser(
        "profile",
        help="capture a device trace (jax.profiler) on a live worker",
    )
    p.add_argument("worker_id",
                   help="worker id (hex prefix) or actor id")
    p.add_argument("--seconds", type=float, default=3.0,
                   help="capture window length")
    p.add_argument("--logdir", default=None,
                   help="trace destination on the worker's machine "
                        "(default: /tmp/ray_tpu_profiles/<worker>)")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("down", help="shut the cluster down")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser(
        "lint", help="framework-aware static analysis (RT001-RT012)"
    )
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings")
    p.add_argument("--root", default=None,
                   help="package directory to lint (default: this "
                        "installed ray_tpu package)")
    p.add_argument("--allowlist", default=None,
                   help="allowlist file (default: the package's own "
                        ".rtlint-allowlist)")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("summary", help="task summary by name+state")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("metrics", help="aggregated user metrics")
    p.add_argument("--prometheus", action="store_true")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("timeline", help="task event timeline (json)")
    p.add_argument("--chrome", action="store_true",
                   help="emit chrome://tracing span JSON")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "trace",
        help="per-request trace: waterfall, critical path, stage "
             "breakdown",
    )
    p.add_argument("trace_id", nargs="?", default=None,
                   help="trace id (hex prefix ok); omit to list recent "
                        "traces")
    p.add_argument("--chrome", action="store_true",
                   help="emit this trace as chrome://tracing JSON (flow "
                        "arrows included)")
    p.add_argument("--json", action="store_true",
                   help="raw span dicts / summary rows")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("serve", help="declarative serve operations")
    p.add_argument("action", choices=["deploy", "status", "shutdown"])
    p.add_argument("config", nargs="?", help="YAML config (for deploy)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
