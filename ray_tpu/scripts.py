"""Operator CLI: `python -m ray_tpu <command>`.

Role-equivalent to the reference's `ray` CLI + state API commands
(reference: python/ray/scripts/scripts.py:76, util/state/api.py:781 `ray
list ...`, `ray summary`, `ray timeline`, `ray status`): inspects a running
cluster over the control-plane RPC.  The address comes from --address,
RT_ADDRESS, or /tmp/ray_tpu/latest_address (written by init()).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _resolve_address(addr: Optional[str]) -> str:
    if addr:
        return addr
    if os.environ.get("RT_ADDRESS"):
        return os.environ["RT_ADDRESS"]
    try:
        with open("/tmp/ray_tpu/latest_address") as f:
            return f.read().strip()
    except OSError:
        raise SystemExit(
            "no cluster address (use --address, RT_ADDRESS, or start a "
            "cluster first)"
        )


def _client(addr: Optional[str]):
    from .core.client import Client

    return Client(_resolve_address(addr), kind="driver", pid=os.getpid())


def _print_table(rows, columns):
    if not rows:
        print("(empty)")
        return
    widths = {
        c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
        for c in columns
    }
    print("  ".join(c.upper().ljust(widths[c]) for c in columns))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in columns))


_LIST_COLUMNS = {
    "actors": ["actor_id", "class_name", "state", "name", "pid",
               "num_executed_tasks"],
    "tasks": ["task_id", "name", "state", "error"],
    "nodes": ["node_id", "alive", "resources", "available"],
    "workers": ["worker_id", "node_id", "state", "pid"],
    "objects": ["object_id", "size", "sealed", "inline", "ref_count"],
    "placement_groups": ["pg_id", "strategy", "created", "name"],
}


def cmd_list(args) -> int:
    kind = {"pgs": "placement_groups"}.get(args.kind, args.kind)
    cl = _client(args.address)
    try:
        items = cl.call("list_state", {"kind": kind})["items"]
        if args.json:
            print(json.dumps(items, indent=1, default=str))
        else:
            _print_table(items, _LIST_COLUMNS.get(
                kind, sorted(items[0].keys()) if items else []
            ))
    finally:
        cl.close()
    return 0


def cmd_status(args) -> int:
    cl = _client(args.address)
    try:
        nodes = cl.call("list_state", {"kind": "nodes"})["items"]
        workers = cl.call("list_state", {"kind": "workers"})["items"]
        actors = cl.call("list_state", {"kind": "actors"})["items"]
        total = cl.call("cluster_resources")["resources"]
        avail = cl.call("available_resources")["resources"]
        print(f"nodes: {sum(1 for n in nodes if n.get('alive'))} alive / "
              f"{len(nodes)}")
        print(f"workers: {len(workers)}  actors: "
              f"{sum(1 for a in actors if a['state'] == 'ALIVE')} alive")
        for res in sorted(total):
            used = total[res] - avail.get(res, 0)
            print(f"  {res}: {used:g}/{total[res]:g} used")
    finally:
        cl.close()
    return 0


def cmd_summary(args) -> int:
    """Task summary by name+state (reference: `ray summary tasks`)."""
    cl = _client(args.address)
    try:
        items = cl.call("list_state", {"kind": "tasks"})["items"]
        agg = {}
        for t in items:
            key = (t.get("name", ""), t.get("state", ""))
            agg[key] = agg.get(key, 0) + 1
        rows = [
            {"name": k[0], "state": k[1], "count": v}
            for k, v in sorted(agg.items())
        ]
        _print_table(rows, ["name", "state", "count"])
    finally:
        cl.close()
    return 0


def cmd_metrics(args) -> int:
    cl = _client(args.address)
    try:
        rows = cl.call("list_state", {"kind": "metrics"})["items"]
        if args.prometheus:
            from .util.metrics import prometheus_text

            sys.stdout.write(prometheus_text(rows))
        else:
            _print_table(rows, ["name", "kind", "tags", "value"])
    finally:
        cl.close()
    return 0


def cmd_timeline(args) -> int:
    cl = _client(args.address)
    try:
        items = cl.call("list_state", {"kind": "timeline"})["items"]
        if getattr(args, "chrome", False):
            # chrome://tracing / Perfetto format from span events
            # (reference: `ray timeline` emits the same shape).
            from .util.tracing import chrome_trace

            print(json.dumps(chrome_trace(items)))
        else:
            print(json.dumps(items, indent=1, default=str))
    finally:
        cl.close()
    return 0


def cmd_serve(args) -> int:
    """Declarative Serve operations (reference: `serve deploy/status/
    shutdown` CLI over the schema config)."""
    os.environ.setdefault("RT_ADDRESS", _resolve_address(args.address))
    from ray_tpu import serve as rt_serve

    if args.action == "deploy":
        if not args.config:
            raise SystemExit("serve deploy requires a config file path")
        handles = rt_serve.deploy_config(args.config)
        print(f"deployed {len(handles)} application(s)")
        st = rt_serve.status()
        for name, info in sorted(st.items()):
            print(f"  {name}: {info['running_replicas']}/"
                  f"{info['target_replicas']} replicas")
    elif args.action == "status":
        for name, info in sorted(rt_serve.status().items()):
            print(f"{name}: {info}")
    elif args.action == "shutdown":
        rt_serve.shutdown()
        print("serve shut down")
    return 0


def cmd_dashboard(args) -> int:
    """Serve the web dashboard against a running cluster (reference:
    dashboard/head.py runs as its own process attached to the GCS)."""
    from .dashboard import Dashboard

    dash = Dashboard(
        _resolve_address(args.address), host=args.host, port=args.port
    ).start()
    print(f"dashboard at {dash.url} (ctrl-c to stop)")
    try:
        import signal

        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        pass
    finally:
        dash.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ray_tpu")
    ap.add_argument("--address", default=None)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list", help="list cluster state")
    p.add_argument("kind", choices=[
        "actors", "tasks", "nodes", "workers", "objects",
        "placement_groups", "pgs",
    ])
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("status", help="cluster resource summary")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("summary", help="task summary by name+state")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("metrics", help="aggregated user metrics")
    p.add_argument("--prometheus", action="store_true")
    p.set_defaults(fn=cmd_metrics)

    p = sub.add_parser("timeline", help="task event timeline (json)")
    p.add_argument("--chrome", action="store_true",
                   help="emit chrome://tracing span JSON")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("serve", help="declarative serve operations")
    p.add_argument("action", choices=["deploy", "status", "shutdown"])
    p.add_argument("config", nargs="?", help="YAML config (for deploy)")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
