"""Search spaces + variant generation.

Role-equivalent to the reference's tune search-space API and
BasicVariantGenerator (reference: python/ray/tune/search/sample.py —
grid_search/choice/uniform/randint; search/basic_variant.py — grid
cross-product x num_samples random sampling).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    """A sampled hyperparameter dimension."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower: float, upper: float):
        import math

        self.log_lower, self.log_upper = math.log(lower), math.log(upper)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_lower, self.log_upper))


class RandInt(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> RandInt:
    return RandInt(lower, upper)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, Any]:
    """Marker for exhaustive expansion (one trial per value, crossed with
    every other grid dimension; reference: tune/search/sample.py grid_search)."""
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _collect_grids(space: Dict[str, Any], path=()) -> List[tuple]:
    """All grid_search dimensions in a (possibly nested) space as
    (key-path, values) pairs."""
    out = []
    for k, v in space.items():
        if _is_grid(v):
            out.append((path + (k,), v["grid_search"]))
        elif isinstance(v, dict):
            out.extend(_collect_grids(v, path + (k,)))
    return out


def _resolve(space: Dict[str, Any], grid_assign: Dict[tuple, Any],
             rng: random.Random, path=()) -> Dict[str, Any]:
    cfg: Dict[str, Any] = {}
    for k, v in space.items():
        p = path + (k,)
        if _is_grid(v):
            cfg[k] = grid_assign[p]
        elif isinstance(v, Domain):
            cfg[k] = v.sample(rng)
        elif isinstance(v, dict):
            cfg[k] = _resolve(v, grid_assign, rng, p)
        else:
            cfg[k] = v
    return cfg


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: int = 0,
) -> List[Dict[str, Any]]:
    """Expand a param space into concrete trial configs: the cross-product
    of all grid dimensions (nested dicts included), repeated num_samples
    times with random dimensions re-sampled each repeat
    (reference: basic_variant.py)."""
    rng = random.Random(seed)
    grids = _collect_grids(param_space)
    grid_paths = [p for p, _ in grids]
    grid_values = [vals for _, vals in grids]
    variants: List[Dict[str, Any]] = []
    for _ in range(max(1, num_samples)):
        for combo in itertools.product(*grid_values) if grids else [()]:
            assign = dict(zip(grid_paths, combo))
            variants.append(_resolve(param_space, assign, rng))
    return variants


# ------------------------------------------------------- incremental search


class Searcher:
    """Suggest-based search algorithm (reference: tune/search/searcher.py
    Searcher — suggest/on_trial_complete).  Unlike `generate_variants`'
    eager expansion, a Searcher produces configs one at a time so it can
    condition later suggestions on earlier results."""

    def suggest(self, trial_id: str) -> Dict[str, Any] | None:
        """The next config to try, or None to signal 'nothing right now'
        (the Tuner retries later)."""
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any] | None = None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Adapts the eager variant expansion to the Searcher protocol
    (reference: tune/search/basic_variant.py BasicVariantGenerator)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: int = 0):
        self._variants = generate_variants(param_space, num_samples, seed)
        self._next = 0

    def suggest(self, trial_id: str):
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg

    @property
    def exhausted(self) -> bool:
        return self._next >= len(self._variants)


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from the wrapped searcher (reference:
    tune/search/concurrency_limiter.py ConcurrencyLimiter) — needed when a
    conditioned searcher degrades to random sampling if too many trials run
    before any results arrive."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        assert max_concurrent >= 1
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id: str, result=None):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator search (Bergstra et al. 2011 —
    the algorithm behind HyperOpt; reference: tune/search/hyperopt/
    hyperopt_search.py wraps the same method).  Native implementation over
    this module's Domain types, so no external dependency.

    After ``n_initial`` random trials, completed observations split at the
    ``gamma`` quantile into good/bad sets; per dimension, candidates drawn
    from a kernel density around the GOOD observations are ranked by the
    density ratio l(x)/g(x) and the best of ``n_candidates`` is suggested
    — search concentrates where good results cluster while the bad-set
    density keeps it exploring."""

    def __init__(self, param_space: Dict[str, Any], *, metric: str,
                 mode: str = "min", n_initial: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int = 0):
        assert mode in ("min", "max")
        import numpy as np

        self.space = dict(param_space)
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.py_rng = random.Random(seed)      # Domain.sample's rng type
        self.rng = np.random.default_rng(seed)  # KDE math
        self._np = np
        self._suggested: Dict[str, Dict[str, Any]] = {}
        self._history: list = []  # (config, score)
        for key, dom in self.space.items():
            if not isinstance(dom, (Categorical, Uniform, LogUniform,
                                    RandInt)):
                raise TypeError(
                    f"TPESearcher supports Categorical/Uniform/LogUniform/"
                    f"RandInt domains; {key!r} is {type(dom).__name__}")

    # -- sampling helpers ----------------------------------------------------

    def _random_config(self) -> Dict[str, Any]:
        return {k: d.sample(self.py_rng) for k, d in self.space.items()}

    def _bounds(self, dom):
        """Numeric-space (lo, hi) for a dimension."""
        if isinstance(dom, LogUniform):
            return dom.log_lower, dom.log_upper
        if isinstance(dom, RandInt):
            return float(dom.lower), float(dom.upper - 1)
        return float(dom.lower), float(dom.upper)

    def _numeric_repr(self, dom, value):
        if isinstance(dom, LogUniform):
            return float(self._np.log(value))
        return float(value)

    def _from_numeric(self, dom, x):
        np = self._np
        lo, hi = self._bounds(dom)
        x = float(np.clip(x, lo, hi))
        if isinstance(dom, LogUniform):
            return float(np.exp(x))
        if isinstance(dom, RandInt):
            return int(round(x))
        return x

    def _propose_dim(self, dom, good, bad):
        """Best-of-candidates by the l/g density ratio for one dimension."""
        np = self._np
        if isinstance(dom, Categorical):
            cats = list(dom.categories)

            def weights(obs):
                w = np.ones(len(cats))  # +1 smoothing
                for v in obs:
                    w[cats.index(v)] += 1
                return w / w.sum()

            wl, wg = weights(good), weights(bad)
            idx = self.rng.choice(len(cats), size=self.n_candidates, p=wl)
            best = idx[int(np.argmax(wl[idx] / wg[idx]))]
            return cats[int(best)]
        g = np.array([self._numeric_repr(dom, v) for v in good])
        b = np.array([self._numeric_repr(dom, v) for v in bad])
        lo, hi = self._bounds(dom)
        span = max(hi - lo, 1e-12)
        bw_g = max(span / max(len(g), 1), span * 0.05)
        bw_b = max(span / max(len(b), 1), span * 0.05)

        def density(x, pts, bw):
            if len(pts) == 0:
                return np.full_like(x, 1.0 / span)
            d = (x[:, None] - pts[None, :]) / bw
            return np.exp(-0.5 * d * d).sum(axis=1) / (len(pts) * bw) \
                + 1e-12

        centers = self.rng.choice(g, size=self.n_candidates)
        cand = np.clip(centers + self.rng.normal(0, bw_g,
                                                 self.n_candidates),
                       lo, hi)
        ratio = density(cand, g, bw_g) / density(cand, b, bw_b)
        return self._from_numeric(dom, float(cand[int(np.argmax(ratio))]))

    # -- Searcher protocol ---------------------------------------------------

    def suggest(self, trial_id: str):
        if len(self._history) < self.n_initial:
            cfg = self._random_config()
        else:
            np = self._np
            scores = np.array([s for _, s in self._history])
            if self.mode == "max":
                scores = -scores
            cut = np.quantile(scores, self.gamma)
            configs = [c for c, _ in self._history]
            good = [c for c, s in zip(configs, scores) if s <= cut]
            bad = [c for c, s in zip(configs, scores) if s > cut]
            if not good or not bad:
                cfg = self._random_config()
            else:
                cfg = {
                    k: self._propose_dim(dom, [c[k] for c in good],
                                         [c[k] for c in bad])
                    for k, dom in self.space.items()
                }
        self._suggested[trial_id] = cfg
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result=None):
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or result is None or self.metric not in result:
            return
        self._history.append((cfg, float(result[self.metric])))
