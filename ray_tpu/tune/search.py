"""Search spaces + variant generation.

Role-equivalent to the reference's tune search-space API and
BasicVariantGenerator (reference: python/ray/tune/search/sample.py —
grid_search/choice/uniform/randint; search/basic_variant.py — grid
cross-product x num_samples random sampling).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    """A sampled hyperparameter dimension."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower: float, upper: float):
        import math

        self.log_lower, self.log_upper = math.log(lower), math.log(upper)

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(self.log_lower, self.log_upper))


class RandInt(Domain):
    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = lower, upper

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> RandInt:
    return RandInt(lower, upper)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, Any]:
    """Marker for exhaustive expansion (one trial per value, crossed with
    every other grid dimension; reference: tune/search/sample.py grid_search)."""
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def _collect_grids(space: Dict[str, Any], path=()) -> List[tuple]:
    """All grid_search dimensions in a (possibly nested) space as
    (key-path, values) pairs."""
    out = []
    for k, v in space.items():
        if _is_grid(v):
            out.append((path + (k,), v["grid_search"]))
        elif isinstance(v, dict):
            out.extend(_collect_grids(v, path + (k,)))
    return out


def _resolve(space: Dict[str, Any], grid_assign: Dict[tuple, Any],
             rng: random.Random, path=()) -> Dict[str, Any]:
    cfg: Dict[str, Any] = {}
    for k, v in space.items():
        p = path + (k,)
        if _is_grid(v):
            cfg[k] = grid_assign[p]
        elif isinstance(v, Domain):
            cfg[k] = v.sample(rng)
        elif isinstance(v, dict):
            cfg[k] = _resolve(v, grid_assign, rng, p)
        else:
            cfg[k] = v
    return cfg


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: int = 0,
) -> List[Dict[str, Any]]:
    """Expand a param space into concrete trial configs: the cross-product
    of all grid dimensions (nested dicts included), repeated num_samples
    times with random dimensions re-sampled each repeat
    (reference: basic_variant.py)."""
    rng = random.Random(seed)
    grids = _collect_grids(param_space)
    grid_paths = [p for p, _ in grids]
    grid_values = [vals for _, vals in grids]
    variants: List[Dict[str, Any]] = []
    for _ in range(max(1, num_samples)):
        for combo in itertools.product(*grid_values) if grids else [()]:
            assign = dict(zip(grid_paths, combo))
            variants.append(_resolve(param_space, assign, rng))
    return variants


# ------------------------------------------------------- incremental search


class Searcher:
    """Suggest-based search algorithm (reference: tune/search/searcher.py
    Searcher — suggest/on_trial_complete).  Unlike `generate_variants`'
    eager expansion, a Searcher produces configs one at a time so it can
    condition later suggestions on earlier results."""

    def suggest(self, trial_id: str) -> Dict[str, Any] | None:
        """The next config to try, or None to signal 'nothing right now'
        (the Tuner retries later)."""
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Dict[str, Any] | None = None) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Adapts the eager variant expansion to the Searcher protocol
    (reference: tune/search/basic_variant.py BasicVariantGenerator)."""

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: int = 0):
        self._variants = generate_variants(param_space, num_samples, seed)
        self._next = 0

    def suggest(self, trial_id: str):
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg

    @property
    def exhausted(self) -> bool:
        return self._next >= len(self._variants)


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions from the wrapped searcher (reference:
    tune/search/concurrency_limiter.py ConcurrencyLimiter) — needed when a
    conditioned searcher degrades to random sampling if too many trials run
    before any results arrive."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        assert max_concurrent >= 1
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id: str):
        if len(self._live) >= self.max_concurrent:
            return None
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id: str, result=None):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)
