"""Tuner + trial controller.

Role-equivalent to the reference's Tuner / TuneController event loop
(reference: tune/tuner.py:44, tune/execution/tune_controller.py:68 step:666)
over trial actors, with experiment state snapshots + resume
(tune/execution/experiment_state.py, Tuner.restore).

Function trainables report via ray_tpu.tune.report(...) (reference:
tune/trainable/function_trainable.py session) or by returning a final
metrics dict.
"""

from __future__ import annotations

import json
import os
import queue
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ..exceptions import RayTpuError
from ..train.config import RunConfig
from ..train.worker_group import _dumps_by_value
from .schedulers import CONTINUE, STOP, FIFOScheduler
from .search import generate_variants

PENDING, RUNNING, TERMINATED, ERROR, STOPPED = (
    "PENDING", "RUNNING", "TERMINATED", "ERROR", "STOPPED",
)


class TuneError(RayTpuError):
    pass


class TuneInterrupted(TuneError):
    """fit() was aborted; the experiment state on disk supports restore()."""


# ---------------------------------------------------------------- session


class _StopTrial(BaseException):
    """Raised inside the trainable when the scheduler stops the trial."""


class _TrialSession:
    def __init__(self, trial_id: str, trial_dir: str,
                 restore_path: Optional[str] = None):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.restore_path = restore_path
        self.queue: "queue.Queue" = queue.Queue()
        self.iteration = 0
        self.stop_requested = False
        # report() blocks until the controller acks the event (reference:
        # function_trainable.py _StatusReporter blocks on _continue_semaphore
        # until the driver consumed the result).  This makes scheduler
        # decisions synchronous with training: a STOP/exploit decision lands
        # before the trainable takes its next step, deterministically.
        # Sequence numbers (not a semaphore) so a backstop timeout cannot
        # leave a stale permit that desynchronizes every later decision:
        # the Nth report waits for the Nth ack, late acks just catch up.
        self._reported_seq = 0
        self._decided_seq = 0
        self._cv = threading.Condition()

    def ack(self, stop: bool = False):
        with self._cv:
            if stop:
                self.stop_requested = True
            self._decided_seq += 1
            self._cv.notify_all()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[str] = None):
        self.iteration += 1
        out = dict(metrics)
        out.setdefault("training_iteration", self.iteration)
        ev = {"kind": "report", "metrics": out}
        if checkpoint is not None:
            ev["checkpoint"] = checkpoint
        self._reported_seq += 1
        seq = self._reported_seq
        self.queue.put(ev)
        # Wait for the controller's decision on THIS report.  The timeout is
        # a deadlock backstop (controller death); the kill path tears the
        # actor down anyway.
        with self._cv:
            self._cv.wait_for(lambda: self._decided_seq >= seq, timeout=60)
        if self.stop_requested:
            raise _StopTrial()


_session: Optional[_TrialSession] = None


def report(metrics: Dict[str, Any], checkpoint: Optional[str] = None) -> None:
    """Report intermediate metrics from inside a trial (reference:
    ray.tune.report / session.report).  ``checkpoint`` is a directory the
    trainable saved this round — registering it enables PBT exploitation
    and best-checkpoint tracking."""
    if _session is None:
        raise RuntimeError("tune.report() called outside a Tuner trial")
    _session.report(metrics, checkpoint=checkpoint)


def get_trial_dir() -> str:
    if _session is None:
        raise RuntimeError("not inside a Tuner trial")
    return _session.trial_dir


def get_checkpoint() -> Optional[str]:
    """Checkpoint directory to restore from, when the controller relaunched
    this trial from another trial's checkpoint (PBT exploit) or a prior run
    (reference: ray.tune.get_checkpoint)."""
    if _session is None:
        raise RuntimeError("not inside a Tuner trial")
    return _session.restore_path


@ray_tpu.remote(max_concurrency=4)
class _TrialRunner:
    """Hosts one trial's function trainable; reports stream through poll()."""

    def __init__(self):
        self._session: Optional[_TrialSession] = None

    def run(self, fn_blob: bytes, config: dict, trial_id: str,
            trial_dir: str):
        global _session
        import ray_tpu.tune.tuner as tuner_mod

        config = dict(config)
        restore_path = config.pop("_tune_restore_path", None)
        sess = _TrialSession(trial_id, trial_dir, restore_path=restore_path)
        self._session = sess
        tuner_mod._session = sess
        final: Dict[str, Any] = {}
        try:
            fn = cloudpickle.loads(fn_blob)
            out = fn(config)
            if isinstance(out, dict):
                out.setdefault("training_iteration", sess.iteration + 1)
                final = out
                sess.queue.put({"kind": "report", "metrics": out})
            sess.queue.put({"kind": "done", "status": TERMINATED,
                            "final": final})
        except _StopTrial:
            sess.queue.put({"kind": "done", "status": STOPPED, "final": {}})
        except BaseException as e:  # noqa: BLE001 — relayed to the driver
            import traceback

            sess.queue.put({
                "kind": "done", "status": ERROR,
                "error": f"{e}\n{traceback.format_exc()}",
            })

    def poll(self) -> List[dict]:
        out: List[dict] = []
        if self._session is None:
            return out
        while True:
            try:
                out.append(self._session.queue.get_nowait())
            except queue.Empty:
                return out

    def request_stop(self):
        """Out-of-band stop (interrupt paths): also releases a reporter
        blocked waiting for its ack so the stop lands immediately."""
        if self._session is not None:
            self._session.ack(stop=True)
        return True

    def ack(self, stop: bool = False):
        """Controller acknowledgment of one report event; ``stop`` rides
        along so stop-and-ack is atomic (no window where the trainable can
        take another step before the stop lands)."""
        if self._session is not None:
            self._session.ack(stop=stop)
        return True


# ------------------------------------------------------------------ trials


class Trial:
    def __init__(self, trial_id: str, config: dict):
        self.trial_id = trial_id
        self.config = config
        self.status = PENDING
        self.last_result: Dict[str, Any] = {}
        self.error: Optional[str] = None
        self.actor = None
        self.run_ref = None
        self.latest_checkpoint: Optional[str] = None
        # Set when a PBT exploit decision is in flight: the trial stops,
        # then relaunches from the source trial's checkpoint.
        self.pending_exploit: Optional[dict] = None

    def to_json(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "error": self.error,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Trial":
        t = cls(d["trial_id"], d["config"])
        t.status = d["status"]
        t.last_result = d.get("last_result", {})
        t.error = d.get("error")
        return t


class Result:
    def __init__(self, trial: Trial):
        self.config = trial.config
        self.metrics = trial.last_result
        self.error = trial.error
        self.trial_id = trial.trial_id
        self.status = trial.status


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        return Result(self._trials[i])

    def __iter__(self):
        return (Result(t) for t in self._trials)

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise TuneError("no metric given (TuneConfig.metric or argument)")
        best = None
        for t in self._trials:
            if metric not in t.last_result:
                continue
            v = t.last_result[metric]
            if best is None or (v > best[0] if mode == "max" else v < best[0]):
                best = (v, t)
        if best is None:
            raise TuneError(f"no trial reported metric {metric!r}")
        return Result(best[1])

    def get_dataframe(self):
        rows = [
            {"trial_id": t.trial_id, "status": t.status,
             **{f"config/{k}": v for k, v in t.config.items()},
             **t.last_result}
            for t in self._trials
        ]
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows


# ------------------------------------------------------------------- config


class TuneConfig:
    """(reference: tune/tune_config.py TuneConfig)"""

    def __init__(
        self,
        metric: Optional[str] = None,
        mode: str = "min",
        num_samples: int = 1,
        max_concurrent_trials: Optional[int] = None,
        scheduler=None,
        search_alg=None,
        resources_per_trial: Optional[Dict[str, float]] = None,
        seed: int = 0,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.max_concurrent_trials = max_concurrent_trials
        self.scheduler = scheduler or FIFOScheduler()
        # Incremental searcher (search.Searcher, possibly wrapped in a
        # ConcurrencyLimiter); None -> eager variant expansion of
        # param_space (reference: tune_config.py search_alg).
        self.search_alg = search_alg
        self.resources_per_trial = resources_per_trial or {"CPU": 1}
        self.seed = seed


# -------------------------------------------------------------------- Tuner


def _trainer_trainable(trainer) -> Callable:
    """Adapt a DataParallelTrainer into a function trainable: each trial
    runs trainer.fit() with the trial's `train_loop_config` merged in
    (reference: BaseTrainer.fit wraps itself as_trainable and runs through
    Tune, train/base_trainer.py:111,567)."""

    def fn(config):
        import copy

        from ..train.config import RunConfig as TrainRunConfig

        t = copy.copy(trainer)
        loop_cfg = dict(t.train_loop_config or {})
        loop_cfg.update(config.get("train_loop_config", config) or {})
        t.train_loop_config = loop_cfg
        base_run = t.run_config or TrainRunConfig()
        t.run_config = TrainRunConfig(
            name="train",
            storage_path=get_trial_dir(),
            failure_config=base_run.failure_config,
            checkpoint_config=base_run.checkpoint_config,
        )
        # Bridge intermediate train.report rounds to the tune session so
        # schedulers (ASHA) can stop bad trials mid-run — a final-only
        # report would make early stopping inert.
        t._report_callback = report
        result = t.fit()
        if result.error is not None:
            raise result.error
        # Every round already reached the tune session via the callback;
        # returning metrics again would duplicate the final report.
        return None

    return fn


def _trainer_trial_resources(trainer, per_trial: Dict[str, float]) -> Dict[str, float]:
    """A trainer trial holds its own actor PLUS a nested worker gang; the
    concurrency cap must count both or trials saturate the cluster and the
    gangs inside them can never start (deadlock)."""
    eff = dict(per_trial)
    sc = trainer.scaling_config
    worker_res = sc.worker_resources()
    for res, amt in worker_res.items():
        eff[res] = eff.get(res, 0.0) + amt * sc.num_workers
    return eff


class Tuner:
    """(reference: tune/tuner.py:44 Tuner; fit -> tune_controller loop).

    `trainable` may be a plain function taking a config dict or a
    DataParallelTrainer/JaxTrainer instance (each trial runs fit() with the
    trial's train_loop_config merged in)."""

    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        _restored_trials: Optional[List[Trial]] = None,
        _experiment_dir: Optional[str] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._trials = _restored_trials
        self._experiment_dir = _experiment_dir
        # Test hook / Ctrl-C analog: set to interrupt fit() with state saved.
        self._abort = threading.Event()

    # -- state ------------------------------------------------------------

    def _state_path(self) -> str:
        return os.path.join(self._experiment_dir, "tuner_state.json")

    def _save_state(self):
        state = {
            "tune_config": {
                "metric": self.tune_config.metric,
                "mode": self.tune_config.mode,
            },
            "trials": [t.to_json() for t in self._trials],
        }
        tmp = self._state_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, indent=1)
        os.replace(tmp, self._state_path())
        # Full config (scheduler, resources, concurrency) isn't JSON;
        # pickle it alongside so restore() keeps the experiment's behavior.
        cfg_path = os.path.join(self._experiment_dir, "tune_config.pkl")
        if not os.path.exists(cfg_path):
            try:
                with open(cfg_path, "wb") as f:
                    f.write(cloudpickle.dumps(self.tune_config))
            except Exception:
                pass

    @classmethod
    def restore(cls, experiment_dir: str, trainable: Callable,
                tune_config: Optional[TuneConfig] = None) -> "Tuner":
        """Resume an interrupted experiment: finished trials keep their
        results; pending/running ones run (again) (reference:
        Tuner.restore + experiment_state.py)."""
        with open(os.path.join(experiment_dir, "tuner_state.json")) as f:
            state = json.load(f)
        trials = [Trial.from_json(d) for d in state["trials"]]
        for t in trials:
            if t.status == RUNNING:  # interrupted mid-run: run again
                t.status = PENDING
        cfg = tune_config
        if cfg is None:
            cfg_path = os.path.join(experiment_dir, "tune_config.pkl")
            try:
                with open(cfg_path, "rb") as f:
                    cfg = cloudpickle.loads(f.read())
            except Exception:
                cfg = TuneConfig(**state["tune_config"])
        return cls(
            trainable,
            tune_config=cfg,
            _restored_trials=trials,
            _experiment_dir=experiment_dir,
        )

    # -- fit ---------------------------------------------------------------

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cfg = self.tune_config
        if self._experiment_dir is None:
            name = self.run_config.name or f"tune_{uuid.uuid4().hex[:8]}"
            storage = self.run_config.storage_path or os.path.join(
                tempfile.gettempdir(), "ray_tpu_results"
            )
            self._experiment_dir = os.path.join(storage, name)
        os.makedirs(self._experiment_dir, exist_ok=True)
        searcher = cfg.search_alg
        if searcher is not None and self.param_space:
            raise TuneError(
                "pass the search space to the search_alg, not param_space — "
                "with search_alg set, param_space would be silently ignored"
            )
        if self._trials is None:
            if searcher is not None:
                # Incremental: trials materialize as the searcher suggests
                # them (bounded by num_samples) in the loop below.
                self._trials = []
            else:
                variants = generate_variants(
                    self.param_space, cfg.num_samples, cfg.seed
                )
                self._trials = [
                    Trial(f"trial_{i:05d}", v) for i, v in enumerate(variants)
                ]
        self._save_state()

        from ..train.trainer import DataParallelTrainer

        trainable = self.trainable
        if isinstance(trainable, DataParallelTrainer):
            # Serialize by value against the USER's module (the train loop's
            # defining module, typically a driver script workers can't
            # import), then wrap.
            import cloudpickle as _cp
            import sys as _sys

            mod = _sys.modules.get(
                getattr(trainable.train_loop, "__module__", None)
            )
            registered = False
            if mod is not None and mod.__name__ != "__main__":
                try:
                    _cp.register_pickle_by_value(mod)
                    registered = True
                except Exception:
                    pass
            try:
                fn_blob = _cp.dumps(_trainer_trainable(trainable))
            finally:
                if registered:
                    try:
                        _cp.unregister_pickle_by_value(mod)
                    except Exception:
                        pass
        else:
            fn_blob = _dumps_by_value(trainable)
        scheduler = cfg.scheduler
        # Placement capacity across every requested resource dimension: an
        # actor beyond capacity would never start and its poll would stall
        # the controller.  Trainer trials count their nested worker gang.
        cluster = ray_tpu.cluster_resources()
        per_trial = cfg.resources_per_trial
        if isinstance(self.trainable, DataParallelTrainer):
            per_trial = _trainer_trial_resources(self.trainable, per_trial)
        capacity = min(
            (int(cluster.get(res, 0) // amt)
             for res, amt in per_trial.items() if amt > 0),
            default=1,
        )
        capacity = max(1, capacity)
        max_concurrent = min(
            cfg.max_concurrent_trials or capacity, capacity
        )
        opts = {"num_cpus": cfg.resources_per_trial.get("CPU", 1)}
        if cfg.resources_per_trial.get("TPU"):
            opts["num_tpus"] = cfg.resources_per_trial["TPU"]

        def launch(trial: Trial, extra_config: Optional[dict] = None):
            trial.actor = _TrialRunner.options(**opts).remote()
            trial_dir = os.path.join(self._experiment_dir, trial.trial_id)
            os.makedirs(trial_dir, exist_ok=True)
            run_cfg = dict(trial.config)
            if extra_config:
                run_cfg.update(extra_config)
            trial.run_ref = trial.actor.run.remote(
                fn_blob, run_cfg, trial.trial_id, trial_dir
            )
            trial.status = RUNNING
            if hasattr(scheduler, "on_trial_add"):
                scheduler.on_trial_add(trial.trial_id, trial.config,
                                       trial_dir)
            self._save_state()

        def scheduler_decision(trial: Trial, metrics: dict):
            """Old-style schedulers take (trial_id, result); context-aware
            ones (wants_context, e.g. PBT) also get checkpoint + config."""
            if getattr(scheduler, "wants_context", False):
                return scheduler.on_result(
                    trial.trial_id, metrics,
                    checkpoint=trial.latest_checkpoint,
                    config=trial.config,
                )
            return scheduler.on_result(trial.trial_id, metrics)

        pending = [t for t in self._trials if t.status == PENDING]
        suggested = len(self._trials)
        running: List[Trial] = []
        try:
            while pending or running or (
                searcher is not None and suggested < cfg.num_samples
            ):
                if self._abort.is_set():
                    raise TuneInterrupted(
                        f"experiment interrupted; restore from "
                        f"{self._experiment_dir}"
                    )
                # Pull new suggestions while capacity remains (reference:
                # tune_controller asks the search algorithm for the next
                # trial as slots free up).
                while (searcher is not None and suggested < cfg.num_samples
                       and len(running) + len(pending) < max_concurrent):
                    trial_id = f"trial_{suggested:05d}"
                    config = searcher.suggest(trial_id)
                    if config is None:
                        if not running and not pending:
                            # Nothing in flight and nothing suggested: the
                            # space is exhausted, not limiter-saturated.
                            suggested = cfg.num_samples
                        break
                    trial = Trial(trial_id, config)
                    suggested += 1
                    self._trials.append(trial)
                    pending.append(trial)
                # Launch up to the concurrency cap (the controller loop —
                # reference: tune_controller.py step:666).
                while pending and len(running) < max_concurrent:
                    trial = pending.pop(0)
                    launch(trial)
                    running.append(trial)
                # Drain reports per trial: one trial's dead worker (OOM,
                # segfault) must fail that trial, not the experiment
                # (reference: tune_controller handles trial-actor failure
                # by erroring the trial).
                still_running: List[Trial] = []
                for trial in running:
                    try:
                        events = ray_tpu.get(
                            trial.actor.poll.remote(), timeout=120
                        )
                    except RayTpuError as e:
                        trial.status = ERROR
                        trial.error = f"trial actor died: {e}"
                        scheduler.on_complete(trial.trial_id,
                                              trial.last_result)
                        if searcher is not None:
                            searcher.on_trial_complete(trial.trial_id,
                                                       trial.last_result)
                        trial.actor = None
                        self._save_state()
                        continue
                    finished = False
                    for ev in events:
                        if ev["kind"] == "report":
                            trial.last_result = ev["metrics"]
                            if ev.get("checkpoint"):
                                trial.latest_checkpoint = ev["checkpoint"]
                            decision = scheduler_decision(
                                trial, ev["metrics"]
                            )
                            stop = decision == STOP
                            if (isinstance(decision, dict)
                                    and decision.get("decision") == "exploit"):
                                # PBT: stop, then relaunch from the source
                                # trial's checkpoint with perturbed config
                                # (reference: pbt.py _exploit).
                                trial.pending_exploit = decision
                                stop = True
                            # Every report must be acked — the trainable is
                            # blocked in report() until the decision lands.
                            try:
                                trial.actor.ack.remote(stop=stop)
                            except Exception:
                                pass
                        elif ev["kind"] == "done":
                            if trial.pending_exploit is not None \
                                    and ev["status"] == STOPPED:
                                exp = trial.pending_exploit
                                trial.pending_exploit = None
                                ray_tpu.kill(trial.actor)
                                trial.config = exp["config"]
                                launch(trial, extra_config={
                                    "_tune_restore_path": exp["restore_from"]
                                })
                                continue
                            finished = True
                            trial.status = ev["status"]
                            if ev.get("final"):
                                trial.last_result = ev["final"]
                            if ev.get("error"):
                                trial.error = ev["error"]
                            scheduler.on_complete(
                                trial.trial_id, trial.last_result
                            )
                            if searcher is not None:
                                searcher.on_trial_complete(
                                    trial.trial_id, trial.last_result
                                )
                    if finished:
                        ray_tpu.kill(trial.actor)
                        trial.actor = None
                        trial.run_ref = None
                        self._save_state()
                    else:
                        still_running.append(trial)
                running = still_running
                if running:
                    time.sleep(0.05)
        finally:
            for t in running:
                if t.actor is not None:
                    try:
                        ray_tpu.kill(t.actor)
                    except Exception:
                        pass
            self._save_state()
        return ResultGrid(self._trials, cfg.metric, cfg.mode)
