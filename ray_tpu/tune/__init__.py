"""ray_tpu.tune: hyperparameter search over trial actors.

Role-equivalent to Ray Tune (reference: python/ray/tune — Tuner,
TuneController, search spaces, ASHA scheduler, experiment resume), scaled to
the TPU-first framework: trials are actors, TPU trials reserve chips via
resources_per_trial, and gang trials compose with ray_tpu.train inside the
trainable.
"""

from .schedulers import (
    ASHAScheduler,
    HyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from .search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    sample_from,
    uniform,
)
from .tuner import (
    Result,
    ResultGrid,
    TuneConfig,
    TuneError,
    TuneInterrupted,
    Tuner,
    get_checkpoint,
    get_trial_dir,
    report,
)

__all__ = [
    "Tuner", "TuneConfig", "TuneError", "TuneInterrupted",
    "Result", "ResultGrid", "report", "get_trial_dir", "get_checkpoint",
    "grid_search", "choice", "uniform", "loguniform", "randint",
    "TPESearcher",
    "sample_from", "ASHAScheduler", "HyperBandScheduler", "FIFOScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining", "Searcher", "BasicVariantGenerator",
    "ConcurrencyLimiter",
]
