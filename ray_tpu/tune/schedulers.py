"""Trial schedulers: FIFO and ASHA early stopping.

Role-equivalent to the reference's FIFOScheduler and AsyncHyperBandScheduler
(reference: tune/schedulers/trial_scheduler.py, async_hyperband.py:36 — the
asynchronous successive-halving algorithm: rungs at grace_period *
reduction_factor^k; a trial reaching a rung continues only if its metric is
in the top 1/reduction_factor of results recorded at that rung).
"""

from __future__ import annotations

import math
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """Run every trial to completion."""

    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_complete(self, trial_id: str, result: Dict) -> None:
        pass


class _Rung:
    __slots__ = ("t", "recorded")

    def __init__(self, t: float):
        self.t = t
        self.recorded: Dict[str, float] = {}

    def cutoff(self, reduction_factor: float):
        """Values are normalized bigger-is-better; a trial survives the rung
        only if its value is >= the (1 - 1/rf) quantile of recorded values
        (keep the top 1/rf fraction — reference: async_hyperband.py cutoff
        via nanpercentile)."""
        values = sorted(self.recorded.values())
        k = int(math.floor(len(values) * (1 - 1 / reduction_factor)))
        if k <= 0:
            return None
        return values[min(k, len(values) - 1)]


class ASHAScheduler:
    """Asynchronous successive halving (reference: async_hyperband.py:36)."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.reduction_factor = reduction_factor
        self.rungs: List[_Rung] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(_Rung(t))
            t *= reduction_factor
        self.rungs.reverse()  # highest rung first (match a trial's furthest)

    def _value(self, result: Dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v  # normalize to bigger=better

    def on_result(self, trial_id: str, result: Dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        t = result[self.time_attr]
        value = self._value(result)
        decision = CONTINUE
        for rung in self.rungs:
            if t < rung.t or trial_id in rung.recorded:
                continue
            rung.recorded[trial_id] = value
            cutoff = rung.cutoff(self.reduction_factor)
            if cutoff is not None and value < cutoff:
                decision = STOP
            break  # only the highest newly-reached rung counts
        return decision

    def on_complete(self, trial_id: str, result: Dict) -> None:
        if result and self.metric in result and self.time_attr in result:
            for rung in self.rungs:
                if result[self.time_attr] >= rung.t \
                        and trial_id not in rung.recorded:
                    rung.recorded[trial_id] = self._value(result)


class MedianStoppingRule:
    """Stop a trial whose running mean falls below the median of other
    trials' running means (reference: tune/schedulers/median_stopping_rule.py
    MedianStoppingRule — the original Vizier rule)."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.grace_period = grace_period
        self.min_samples_required = min_samples_required
        self._values: Dict[str, List[float]] = {}

    def _value(self, result: Dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    def on_result(self, trial_id: str, result: Dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        self._values.setdefault(trial_id, []).append(self._value(result))
        if result[self.time_attr] < self.grace_period:
            return CONTINUE
        other_means = [
            sum(vs) / len(vs)
            for tid, vs in self._values.items()
            if tid != trial_id and vs
        ]
        if len(other_means) < self.min_samples_required:
            return CONTINUE
        import statistics

        median = statistics.median(other_means)
        mine = self._values[trial_id]
        if sum(mine) / len(mine) < median:
            return STOP
        return CONTINUE

    def on_complete(self, trial_id: str, result: Dict) -> None:
        pass


class PopulationBasedTraining:
    """PBT: bottom-quantile trials clone a top-quantile trial's checkpoint
    and continue with perturbed hyperparameters (reference:
    tune/schedulers/pbt.py PopulationBasedTraining — exploit via
    checkpoint copy, explore via resample-or-scale).

    Requires cooperative trainables: they must pass ``checkpoint=<dir>`` to
    ``tune.report`` and restore from ``tune.get_checkpoint()`` at start.
    The Tuner relaunches an exploited trial's function from the source
    trial's checkpoint with the perturbed config.
    """

    #: Tuner passes (result, checkpoint=..., config=...) to on_result.
    wants_context = True

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 5,
        hyperparam_mutations: Dict | None = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: int = 0,
    ):
        assert mode in ("min", "max")
        assert 0 < quantile_fraction <= 0.5
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.perturbation_interval = perturbation_interval
        self.hyperparam_mutations = hyperparam_mutations or {}
        self.quantile_fraction = quantile_fraction
        self.resample_probability = resample_probability
        import random

        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}        # latest normalized score
        self._checkpoints: Dict[str, str] = {}     # latest checkpoint dir
        self._configs: Dict[str, Dict] = {}
        self._last_perturb: Dict[str, float] = {}
        self.num_exploits = 0

    def _value(self, result: Dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v

    # -- explore -------------------------------------------------------------

    def _explore(self, config: Dict) -> Dict:
        """Perturb the source config (reference: pbt.py explore: resample
        with probability ``resample_probability``, else scale numeric values
        by 1.2/0.8 or step categorical values to a neighbor)."""
        new = dict(config)
        for key, spec in self.hyperparam_mutations.items():
            resample = self._rng.random() < self.resample_probability
            cur = new.get(key)
            if callable(spec):
                if resample or not isinstance(cur, (int, float)):
                    new[key] = spec()
                else:
                    new[key] = cur * self._rng.choice((0.8, 1.2))
            elif isinstance(spec, (list, tuple)):
                if resample or cur not in spec:
                    new[key] = self._rng.choice(list(spec))
                else:
                    i = list(spec).index(cur)
                    j = max(0, min(len(spec) - 1,
                                   i + self._rng.choice((-1, 1))))
                    new[key] = list(spec)[j]
            elif hasattr(spec, "sample"):  # search.Domain
                if resample or not isinstance(cur, (int, float)):
                    new[key] = spec.sample(self._rng)
                else:
                    new[key] = cur * self._rng.choice((0.8, 1.2))
            elif isinstance(cur, (int, float)):
                new[key] = cur * self._rng.choice((0.8, 1.2))
        return new

    # -- scheduler protocol ---------------------------------------------------

    def on_trial_add(self, trial_id: str, config: Dict, trial_dir: str):
        self._configs[trial_id] = dict(config)

    def on_result(self, trial_id: str, result: Dict, checkpoint=None,
                  config=None):
        if config is not None:
            self._configs[trial_id] = dict(config)
        if checkpoint:
            self._checkpoints[trial_id] = checkpoint
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        t = result[self.time_attr]
        self._scores[trial_id] = self._value(result)
        if t - self._last_perturb.get(trial_id, 0) < self.perturbation_interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        if len(self._scores) < 2:
            return CONTINUE
        ranked = sorted(self._scores, key=self._scores.get)
        k = max(1, int(len(ranked) * self.quantile_fraction))
        bottom, top = ranked[:k], ranked[-k:]
        if trial_id not in bottom:
            return CONTINUE
        sources = [tid for tid in top
                   if tid != trial_id and tid in self._checkpoints]
        if not sources:
            return CONTINUE
        source = self._rng.choice(sources)
        self.num_exploits += 1
        return {
            "decision": "exploit",
            "config": self._explore(self._configs[source]),
            "restore_from": self._checkpoints[source],
            "source": source,
        }

    def on_complete(self, trial_id: str, result: Dict) -> None:
        # Completed trials stay in the population: their final scores keep
        # the quantiles honest and their checkpoints remain valid exploit
        # sources for stragglers.
        pass


class HyperBandScheduler:
    """Multi-bracket successive halving (reference:
    tune/schedulers/hyperband.py HyperBandScheduler — brackets trade off
    exploration breadth vs per-trial budget; Li et al. 2018).

    The asynchronous (infinite-horizon) variant: each trial is assigned
    round-robin to one of ``s_max + 1`` brackets; bracket ``s`` runs ASHA
    rungs starting at ``grace_period * eta**s`` so aggressive brackets
    stop early and conservative brackets let trials run long.  Decisions
    are rung-local and asynchronous — no pause/promote barrier — which is
    the same trade the reference's ASHA docs recommend over synchronous
    HyperBand for distributed execution.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        max_t: int = 81,
        grace_period: int = 1,
        eta: float = 3,
    ):
        assert mode in ("min", "max")
        if eta <= 1:
            raise ValueError(f"eta must be > 1, got {eta}")
        if grace_period > max_t:
            raise ValueError(
                f"grace_period ({grace_period}) must be <= max_t ({max_t})")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.eta = eta
        self._brackets: List[ASHAScheduler] = []
        s = 0
        g = grace_period
        while g <= max_t:
            self._brackets.append(ASHAScheduler(
                metric=metric, mode=mode, time_attr=time_attr,
                max_t=max_t, grace_period=g, reduction_factor=eta,
            ))
            s += 1
            g = grace_period * int(eta ** s)
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def _bracket_of(self, trial_id: str) -> ASHAScheduler:
        idx = self._assignment.get(trial_id)
        if idx is None:
            idx = self._assignment[trial_id] = \
                self._next % len(self._brackets)
            self._next += 1
        return self._brackets[idx]

    @property
    def num_brackets(self) -> int:
        return len(self._brackets)

    def on_result(self, trial_id: str, result: Dict) -> str:
        return self._bracket_of(trial_id).on_result(trial_id, result)

    def on_complete(self, trial_id: str, result: Dict) -> None:
        self._bracket_of(trial_id).on_complete(trial_id, result)
