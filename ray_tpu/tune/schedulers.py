"""Trial schedulers: FIFO and ASHA early stopping.

Role-equivalent to the reference's FIFOScheduler and AsyncHyperBandScheduler
(reference: tune/schedulers/trial_scheduler.py, async_hyperband.py:36 — the
asynchronous successive-halving algorithm: rungs at grace_period *
reduction_factor^k; a trial reaching a rung continues only if its metric is
in the top 1/reduction_factor of results recorded at that rung).
"""

from __future__ import annotations

import math
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    """Run every trial to completion."""

    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE

    def on_complete(self, trial_id: str, result: Dict) -> None:
        pass


class _Rung:
    __slots__ = ("t", "recorded")

    def __init__(self, t: float):
        self.t = t
        self.recorded: Dict[str, float] = {}

    def cutoff(self, reduction_factor: float):
        """Values are normalized bigger-is-better; a trial survives the rung
        only if its value is >= the (1 - 1/rf) quantile of recorded values
        (keep the top 1/rf fraction — reference: async_hyperband.py cutoff
        via nanpercentile)."""
        values = sorted(self.recorded.values())
        k = int(math.floor(len(values) * (1 - 1 / reduction_factor)))
        if k <= 0:
            return None
        return values[min(k, len(values) - 1)]


class ASHAScheduler:
    """Asynchronous successive halving (reference: async_hyperband.py:36)."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        time_attr: str = "training_iteration",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 3,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.reduction_factor = reduction_factor
        self.rungs: List[_Rung] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(_Rung(t))
            t *= reduction_factor
        self.rungs.reverse()  # highest rung first (match a trial's furthest)

    def _value(self, result: Dict) -> float:
        v = float(result[self.metric])
        return v if self.mode == "max" else -v  # normalize to bigger=better

    def on_result(self, trial_id: str, result: Dict) -> str:
        if self.metric not in result or self.time_attr not in result:
            return CONTINUE
        t = result[self.time_attr]
        value = self._value(result)
        decision = CONTINUE
        for rung in self.rungs:
            if t < rung.t or trial_id in rung.recorded:
                continue
            rung.recorded[trial_id] = value
            cutoff = rung.cutoff(self.reduction_factor)
            if cutoff is not None and value < cutoff:
                decision = STOP
            break  # only the highest newly-reached rung counts
        return decision

    def on_complete(self, trial_id: str, result: Dict) -> None:
        if result and self.metric in result and self.time_attr in result:
            for rung in self.rungs:
                if result[self.time_attr] >= rung.t \
                        and trial_id not in rung.recorded:
                    rung.recorded[trial_id] = self._value(result)
