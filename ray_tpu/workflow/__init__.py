"""ray_tpu.workflow: durable workflows on top of tasks.

Role-equivalent to the reference's workflow library
(reference: python/ray/workflow/api.py:123 run/:177 run_async,
workflow_executor.py, workflow_storage.py — steps execute as tasks, every
step's result is persisted, and re-running the same workflow_id resumes from
the last completed step instead of recomputing).

    a = workflow.step(load)(path)
    b = workflow.step(transform)(a)
    result = workflow.run(b, workflow_id="etl-1")   # crash-safe
"""

from __future__ import annotations

import os
import pickle
import threading
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu

DEFAULT_STORAGE = "/tmp/ray_tpu_workflows"


class StepNode:
    """One step: a function applied to values and/or upstream steps."""

    def __init__(self, fn: Callable, args: tuple, kwargs: dict):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = getattr(fn, "__name__", "step")

    def _upstream(self) -> List["StepNode"]:
        ups = [a for a in self.args if isinstance(a, StepNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, StepNode)]
        return ups


def step(fn: Callable) -> Callable[..., StepNode]:
    """Wrap a function so calls build workflow steps (reference:
    the DAG-node binding layer of workflow.run).  A step that RETURNS a
    StepNode continues into that sub-DAG: the sub-steps execute (and
    checkpoint) inside the same workflow, and their result becomes the
    step's result — dynamic workflows (reference: workflow.continuation,
    workflow_executor.py handles steps that return DAGs)."""

    def make(*args, **kwargs) -> StepNode:
        return StepNode(fn, args, kwargs)

    make.__name__ = getattr(fn, "__name__", "step")
    return make


class EventStepNode(StepNode):
    """A step that completes when an external event arrives (reference:
    python/ray/workflow/event_listener.py:11 EventListener.poll_for_event,
    http_event_provider.py).  The poll function runs driver-side on a
    cadence; a non-None return IS the event payload, checkpointed like any
    step result — resume never re-waits for a received event."""

    def __init__(self, poll_fn: Callable, args: tuple, kwargs: dict,
                 poll_interval_s: float = 0.2,
                 timeout_s: Optional[float] = None):
        super().__init__(poll_fn, args, kwargs)
        self.poll_interval_s = poll_interval_s
        self.timeout_s = timeout_s
        self.name = f"event_{self.name}"


def wait_for_event(poll_fn: Callable, *args,
                   poll_interval_s: float = 0.2,
                   timeout_s: Optional[float] = None,
                   **kwargs) -> EventStepNode:
    """Build an event-listener step: the workflow blocks here until
    poll_fn(*args, **kwargs) returns non-None (the event payload).
    Upstream StepNodes in args resolve first, like any step."""
    return EventStepNode(poll_fn, args, kwargs, poll_interval_s, timeout_s)


def kv_event(key: str, *, poll_interval_s: float = 0.2,
             timeout_s: Optional[float] = None) -> EventStepNode:
    """Event = a cluster-KV key appearing.  The KV table rides the head
    snapshot, so the signal survives head restarts; the received payload
    is checkpointed in workflow storage (reference: the KV/HTTP event
    providers commit events durably before the workflow advances)."""

    def poll_kv():
        from ray_tpu.core.context import ctx

        raw = ctx.client.kv_get(key)
        return None if raw is None else raw

    poll_kv.__name__ = f"kv[{key}]"
    return wait_for_event(poll_kv, poll_interval_s=poll_interval_s,
                          timeout_s=timeout_s)


class _Storage:
    """File-per-step result store (reference: workflow_storage.py)."""

    def __init__(self, workflow_id: str, base: Optional[str]):
        self.dir = os.path.join(base or DEFAULT_STORAGE, workflow_id)
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"{step_id}.pkl")

    def has(self, step_id: str) -> bool:
        return os.path.exists(self._path(step_id))

    def load(self, step_id: str) -> Any:
        with open(self._path(step_id), "rb") as f:
            return pickle.load(f)

    def save(self, step_id: str, value: Any):
        tmp = self._path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(value, f)
        os.replace(tmp, self._path(step_id))


def _topo_order(root: StepNode) -> List[StepNode]:
    order: List[StepNode] = []
    seen: set = set()

    def visit(node: StepNode):
        if id(node) in seen:
            return
        seen.add(id(node))
        for up in node._upstream():
            visit(up)
        order.append(node)

    visit(root)
    return order


def run(node: StepNode, *, workflow_id: str,
        storage: Optional[str] = None, _prefix: str = "") -> Any:
    """Execute the workflow durably: each step runs as a cluster task, its
    result persists before the next step starts, and a re-run with the same
    workflow_id skips completed steps (reference: api.py:123 run +
    workflow_state_from_storage.py resume).

    Event steps (EventStepNode) poll driver-side until their event
    arrives; steps returning StepNodes continue into the returned sub-DAG
    (checkpointed under the parent step's id namespace)."""
    import time

    if not ray_tpu.is_initialized():
        ray_tpu.init()
    store = _Storage(workflow_id, storage)
    order = _topo_order(node)
    # Deterministic step ids: topological index + function name (stable for
    # the same DAG shape across runs — the resume key).  Sub-DAG steps get
    # the parent step's id as a dotted prefix.  Ids become FILENAMES in
    # _Storage, so path separators in step names (e.g. a kv_event key like
    # "jobs/123/done") must be sanitized out.
    ids = {
        id(n): f"{_prefix}{i:03d}_{n.name}".replace(os.sep, ".").replace(
            "/", ".")
        for i, n in enumerate(order)
    }
    results: Dict[int, Any] = {}
    remaining = [n for n in order]
    inflight: Dict[Any, StepNode] = {}  # ref -> node
    # Ready event steps being polled: node -> first-poll time.
    polling: Dict[int, float] = {}
    first_error: Optional[BaseException] = None

    def finish(n: StepNode, value: Any):
        nonlocal first_error
        if isinstance(value, StepNode):
            if first_error is not None:
                # A sibling already failed: launching a whole sub-DAG now
                # would delay error propagation with fresh cluster work.
                # The unexecuted continuation isn't checkpointed, so a
                # resume re-runs the parent and continues normally.
                return
            # Dynamic continuation: execute the returned sub-DAG in the
            # same workflow; ITS result is this step's durable result.
            try:
                value = run(value, workflow_id=workflow_id,
                            storage=storage,
                            _prefix=ids[id(n)].replace("/", ".") + ".")
            except BaseException as e:  # noqa: BLE001
                if first_error is None:
                    first_error = e
                return
        store.save(ids[id(n)], value)
        results[id(n)] = value

    while remaining or inflight or polling:
        # Launch every step whose upstreams are resolved: independent
        # branches run concurrently (reference: workflow_executor.py runs
        # all ready tasks).
        still_waiting: List[StepNode] = []
        for n in remaining:
            if first_error is not None:
                still_waiting.append(n)
                continue
            sid = ids[id(n)]
            if store.has(sid):
                results[id(n)] = store.load(sid)
                continue
            if not all(id(u) in results for u in n._upstream()):
                still_waiting.append(n)
                continue
            if isinstance(n, EventStepNode):
                polling.setdefault(id(n), time.monotonic())
                still_waiting.append(n)
                continue
            args = tuple(
                results[id(a)] if isinstance(a, StepNode) else a
                for a in n.args
            )
            kwargs = {
                k: results[id(v)] if isinstance(v, StepNode) else v
                for k, v in n.kwargs.items()
            }
            ref = ray_tpu.remote(n.fn).remote(*args, **kwargs)
            inflight[ref] = n
        remaining = still_waiting

        # Poll ready event steps once per loop turn (driver-side — the
        # listener is control-plane work, not a cluster task).
        min_interval = None
        for n in list(remaining):
            if id(n) not in polling or first_error is not None:
                continue
            args = tuple(
                results[id(a)] if isinstance(a, StepNode) else a
                for a in n.args
            )
            kwargs = {
                k: results[id(v)] if isinstance(v, StepNode) else v
                for k, v in n.kwargs.items()
            }
            try:
                event = n.fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                if first_error is None:
                    first_error = e
                polling.pop(id(n), None)
                remaining.remove(n)
                continue
            if event is not None:
                polling.pop(id(n), None)
                remaining.remove(n)
                finish(n, event)
            elif (n.timeout_s is not None
                    and time.monotonic() - polling[id(n)] > n.timeout_s):
                polling.pop(id(n), None)
                remaining.remove(n)
                if first_error is None:
                    first_error = TimeoutError(
                        f"event step {ids[id(n)]} saw no event within "
                        f"{n.timeout_s}s")
            else:
                min_interval = (n.poll_interval_s if min_interval is None
                                else min(min_interval, n.poll_interval_s))

        if not inflight:
            if polling and first_error is None:
                time.sleep(min_interval or 0.2)
                continue
            if first_error is not None:
                raise first_error
            continue
        ready, _ = ray_tpu.wait(
            list(inflight), num_returns=1,
            timeout=min_interval if min_interval is not None else 3600)
        for ref in ready:
            n = inflight.pop(ref)
            try:
                value = ray_tpu.get(ref)
            except BaseException as e:  # noqa: BLE001 — raised after drain
                if first_error is None:
                    first_error = e
                continue
            finish(n, value)
    if first_error is not None:
        raise first_error
    return results[id(node)]


class WorkflowRun:
    def __init__(self, thread: threading.Thread, box: dict):
        self._thread = thread
        self._box = box

    def result(self, timeout: Optional[float] = None) -> Any:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("workflow still running")
        if "error" in self._box:
            raise self._box["error"]
        return self._box["value"]


def run_async(node: StepNode, *, workflow_id: str,
              storage: Optional[str] = None) -> WorkflowRun:
    """(reference: api.py:177 run_async)"""
    box: dict = {}

    def go():
        try:
            box["value"] = run(node, workflow_id=workflow_id, storage=storage)
        except BaseException as e:  # noqa: BLE001 — delivered via result()
            box["error"] = e

    t = threading.Thread(target=go, daemon=True)
    t.start()
    return WorkflowRun(t, box)


def list_workflows(storage: Optional[str] = None) -> List[str]:
    base = storage or DEFAULT_STORAGE
    try:
        return sorted(os.listdir(base))
    except FileNotFoundError:
        return []


def delete(workflow_id: str, storage: Optional[str] = None):
    import shutil

    shutil.rmtree(os.path.join(storage or DEFAULT_STORAGE, workflow_id),
                  ignore_errors=True)
