"""Batch iteration: block streams → fixed-size batches → device.

Role-equivalent to the reference's batcher/prefetcher stack (reference:
data/_internal/block_batching/iter_batches.py — resolve→format→batch
pipeline with prefetching) collapsed to two generators: a row-carry batcher
and a one-slot device_put double buffer.  `jax.device_put` is async — the
next batch's host→HBM copy overlaps the caller's compute on the current
batch, which is what keeps the chip from starving (BASELINE north star:
Arrow→device ingest with no input starvation).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from .block import Batch, Block


def batches_from_blocks(
    blocks: Iterator[Block],
    batch_size: int,
    batch_format: str = "numpy",
    drop_last: bool = False,
) -> Iterator[Any]:
    """Re-chunk a block stream into exact batch_size batches, carrying
    remainder rows across block boundaries."""
    carry: Optional[Batch] = None
    for block in blocks:
        if block.num_rows == 0:
            continue
        batch = block.to_numpy()
        if carry is not None:
            batch = {
                k: np.concatenate([carry[k], batch[k]]) for k in batch
            }
            carry = None
        n = len(next(iter(batch.values()))) if batch else 0
        off = 0
        while n - off >= batch_size:
            yield _format({k: v[off:off + batch_size] for k, v in batch.items()},
                          batch_format)
            off += batch_size
        if off < n:
            carry = {k: v[off:] for k, v in batch.items()}
    if carry is not None and not drop_last:
        yield _format(carry, batch_format)


def _format(batch: Batch, batch_format: str) -> Any:
    if batch_format == "numpy":
        return batch
    if batch_format == "pandas":
        return Block.from_batch(batch).to_pandas()
    if batch_format == "pyarrow":
        return Block.from_batch(batch).to_arrow()
    raise ValueError(f"unknown batch_format {batch_format!r}")


def device_prefetch(batches: Iterator[Batch], device: Any) -> Iterator[Any]:
    """One-slot lookahead onto an accelerator: batch N+1's device_put is
    issued (async) before batch N is yielded, so transfer overlaps the
    consumer's step."""
    import jax

    dev = None if device is True else device
    prev = None
    for batch in batches:
        cur = {
            k: jax.device_put(v, dev) if v.dtype != object else v
            for k, v in batch.items()
        }
        if prev is not None:
            yield prev
        prev = cur
    if prev is not None:
        yield prev


class DataIterator:
    """A shard handle from streaming_split — picklable, usable inside a
    Train worker (reference: data/iterator.py DataIterator handed out by
    streaming_split; session.get_dataset_shard returns one)."""

    def __init__(self, coordinator: Any, split_index: int):
        self._coord = coordinator
        self._split = split_index

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_blocks: int = 2,
        device: Any = None,
    ) -> Iterator[Any]:
        import ray_tpu

        from .context import DataContext

        batch_size = batch_size or DataContext.get_current().default_batch_size
        epoch = ray_tpu.get(self._coord.begin_epoch.remote(self._split))

        def blocks() -> Iterator[Block]:
            pending: List[Any] = []
            pos = 0
            done = False
            while pending or not done:
                # Keep `prefetch_blocks` next_block requests in flight.
                while not done and len(pending) <= prefetch_blocks:
                    pending.append(
                        self._coord.next_block.remote(self._split, epoch, pos)
                    )
                    pos += 1
                ref = ray_tpu.get(pending.pop(0))
                if ref is None:
                    done = True
                    pending.clear()
                    break
                yield ray_tpu.get(ref)

        out = batches_from_blocks(blocks(), batch_size, batch_format, drop_last)
        if device is not None:
            out = device_prefetch(out, device)
        return out

    def iter_rows(self) -> Iterator[Dict]:
        for batch in self.iter_batches(batch_format="numpy"):
            keys = list(batch)
            if not keys:
                continue
            for i in range(len(batch[keys[0]])):
                yield {k: batch[k][i] for k in keys}

    def __repr__(self) -> str:
        return f"DataIterator(split={self._split})"
