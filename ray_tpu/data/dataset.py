"""Dataset: lazy plan + bounded-window streaming execution over the cluster.

Role-equivalent to the reference's Dataset / streaming executor (reference:
python/ray/data/dataset.py:139 — map_batches:383, repartition:1042,
split:1337, iter_batches:3675, streaming_split via
data/_internal/execution/operators/output_splitter.py;
data/_internal/execution/streaming_executor.py:48).  Design deviation: the
reference builds logical→physical plans with an optimizer and a
resource-budgeted operator state machine; here the plan is a list of
(source, op-chain) parts and execution is a pull-based window of remote
tasks — each task runs the whole chain for one block (operator fusion by
construction, which is what the reference's optimizer does to map chains
anyway).
"""

from __future__ import annotations

import builtins
import functools
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

import ray_tpu

from .block import Batch, Block
from .context import DataContext
from .logical import LogicalOp, LogicalPlan

# A part is one block's production recipe: a source (callable returning a
# Block, or an ObjectRef of a materialized Block) plus the op chain to apply.
Source = Any
Op = Callable[[Block], Block]


class _TimedOp:
    """A named per-block op.  The name feeds Dataset.stats()' per-operator
    rows/wall breakdown (reference: each physical operator carries
    OpRuntimeMetrics — _internal/execution/interfaces/op_runtime_metrics.py).
    Execution cost is one attribute lookup; timing only happens on the
    stats path."""

    __slots__ = ("name", "fn")

    def __init__(self, name: str, fn: Op):
        self.name = name
        self.fn = fn

    def __call__(self, block: Block) -> Block:
        return self.fn(block)


def _op_name(op: Op) -> str:
    if isinstance(op, _TimedOp):
        return op.name
    if isinstance(op, _StatefulBatchOp):
        return f"MapBatches({op.fn_cls.__name__})"
    return getattr(op, "__name__", type(op).__name__)


class _ReadTask:
    """Picklable file-read source with pushdown knobs (reference:
    datasource ReadTask + the logical Read op that column/limit pushdown
    rules rewrite — logical/rules/).  ``columns`` prunes at the parquet
    reader (only those columns are decoded); ``limit`` caps rows per part.
    """

    __slots__ = ("kind", "files", "columns", "limit", "reader_kwargs")

    SUPPORTS_COLUMNS = {"parquet"}

    def __init__(self, kind: str, files: List[str],
                 columns: Optional[List[str]] = None,
                 limit: Optional[int] = None, reader_kwargs=None):
        self.kind = kind
        self.files = files
        self.columns = columns
        self.limit = limit
        self.reader_kwargs = reader_kwargs or {}

    def _read_one(self, f: str) -> Block:
        if self.kind == "parquet":
            import pyarrow.parquet as pq

            return Block.from_arrow(pq.read_table(f, columns=self.columns))
        if self.kind == "csv":
            import pyarrow.csv as pacsv

            return Block.from_arrow(pacsv.read_csv(f))
        if self.kind == "json":
            import pyarrow.json as pajson

            return Block.from_arrow(pajson.read_json(f))
        if self.kind == "images":
            return _read_image_file(f, **self.reader_kwargs)
        raise ValueError(f"unknown read kind {self.kind!r}")

    def __call__(self) -> Block:
        blocks: List[Block] = []
        rows = 0
        for f in self.files:
            b = self._read_one(f)
            blocks.append(b)
            rows += b.num_rows
            if self.limit is not None and rows >= self.limit:
                break  # row-limited read: later files are never opened
        out = Block.concat(blocks)
        if self.limit is not None and out.num_rows > self.limit:
            out = out.slice(0, self.limit)
        return out

    @property
    def name(self) -> str:
        return f"Read{self.kind.capitalize()}"


def _stage_name(source: Source, ops: List[Op]) -> str:
    """Low-cardinality stage label: the fused op chain this part runs
    (reference: each physical operator exports OpRuntimeMetrics tagged by
    operator name)."""
    parts = [getattr(source, "name", "Source") if callable(source)
             else "Block"]
    parts.extend(_op_name(op) for op in ops)
    return "->".join(parts)[:120]


def _exec_part_body(source: Source, ops: List[Op]) -> Block:
    import time as _time

    t0 = _time.perf_counter()
    block = source() if callable(source) else source
    for op in ops:
        block = op(block)
    _emit_stage_metrics(source, ops, block, _time.perf_counter() - t0)
    return block


def _emit_stage_metrics(source: Source, ops: List[Op], block: Block,
                        wall: float) -> None:
    # Per-stage throughput telemetry: two counters per part (rows and
    # wall-seconds, tagged by the fused stage) — rows/sec is their ratio,
    # and its trend is visible in the head's metrics history.
    try:
        from ray_tpu.util.metrics import get_counter, get_gauge

        tags = {"stage": _stage_name(source, ops)}
        get_counter("ray_tpu_data_rows_total",
                    "Rows produced per dataset stage",
                    tag_keys=("stage",)).inc(block.num_rows, tags=tags)
        get_counter("ray_tpu_data_stage_seconds_total",
                    "Wall seconds spent per dataset stage",
                    tag_keys=("stage",)).inc(wall, tags=tags)
        if wall > 0:
            # pid tag: gauges merge last-writer-wins per (name, tags) at
            # the head, so parallel workers on one stage must stay
            # distinct series (rate over the two counters above gives the
            # stage-wide aggregate).
            import os as _os

            get_gauge("ray_tpu_data_rows_per_sec",
                      "Rows/sec of the most recent part per stage/worker",
                      tag_keys=("stage", "pid")).set(
                block.num_rows / wall,
                tags={**tags, "pid": str(_os.getpid())})
    except Exception:
        pass  # telemetry must never fail a data task


@ray_tpu.remote
def _exec_part(source: Source, ops: List[Op]) -> Block:
    return _exec_part_body(source, ops)


@ray_tpu.remote
def _exec_part_timed(source: Source, ops: List[Op]):
    """The materialize() executor: the block PLUS per-operator timings as
    a second return (submitted with num_returns=2), so Dataset.stats()
    can report the LAST RUN's breakdown without re-executing the plan.
    The timing rows are a few tuples per part — negligible next to the
    block itself."""
    import time as _time

    rows: List[tuple] = []
    t_start = _time.perf_counter()
    t0 = t_start
    block = source() if callable(source) else source
    rows.append((getattr(source, "name", "Source"),
                 _time.perf_counter() - t0, block.num_rows))
    for op in ops:
        t0 = _time.perf_counter()
        block = op(block)
        rows.append((_op_name(op), _time.perf_counter() - t0,
                     block.num_rows))
    _emit_stage_metrics(source, ops, block,
                        _time.perf_counter() - t_start)
    return block, rows


def _aggregate_op_rows(per_part: List[List[tuple]]
                       ) -> List[Dict[str, Any]]:
    """Fold [(op, wall, rows), ...] per part into the stats() operator
    table (tasks / rows_out / wall totals per operator)."""
    operators: List[Dict[str, Any]] = []
    agg: Dict[str, Dict[str, Any]] = {}
    for rows in per_part:
        for name, wall, n_rows in rows:
            ent = agg.get(name)
            if ent is None:
                ent = agg[name] = {
                    "operator": name, "tasks": 0, "rows_out": 0,
                    "wall_total_s": 0.0,
                }
                operators.append(ent)
            ent["tasks"] += 1
            ent["rows_out"] += int(n_rows)
            ent["wall_total_s"] += float(wall)
    for ent in operators:
        ent["wall_total_s"] = round(ent["wall_total_s"], 6)
        ent["wall_mean_s"] = round(
            ent["wall_total_s"] / max(ent["tasks"], 1), 6)
    return operators


@ray_tpu.remote
def _part_rows(source: Source, ops: List[Op]) -> int:
    block = source() if callable(source) else source
    for op in ops:
        block = op(block)
    return block.num_rows


@ray_tpu.remote
def _part_agg(source: Source, ops: List[Op], col: str, kind: str):
    block = source() if callable(source) else source
    for op in ops:
        block = op(block)
    if block.num_rows == 0:
        return None
    arr = block.to_numpy()[col]
    if kind == "sum":
        return (arr.sum(), len(arr))
    if kind == "min":
        return (arr.min(), len(arr))
    if kind == "max":
        return (arr.max(), len(arr))
    if kind == "sumsq":
        arr = arr.astype(np.float64)
        return ((arr.sum(), (arr * arr).sum()), len(arr))
    if kind == "unique":
        return (np.unique(arr).tolist(), len(arr))
    raise ValueError(kind)


@ray_tpu.remote
def _part_group_agg(source: Source, ops: List[Op], key: str,
                    col: Optional[str], kind: str) -> dict:
    """Per-block grouped partials: key -> (accumulator, count)."""
    block = source() if callable(source) else source
    for op in ops:
        block = op(block)
    if block.num_rows == 0:
        return {}
    cols = block.to_numpy()
    keys = cols[key]
    vals = cols[col] if col is not None else None
    out: dict = {}
    for i in builtins.range(len(keys)):
        k = keys[i].item() if hasattr(keys[i], "item") else keys[i]
        acc, cnt = out.get(k, (None, 0))
        if vals is None:
            out[k] = (None, cnt + 1)
            continue
        v = vals[i]
        if acc is None:
            acc = v
        elif kind in ("sum", "mean"):
            acc = acc + v
        elif kind == "min":
            acc = min(acc, v)
        elif kind == "max":
            acc = max(acc, v)
        out[k] = (acc, cnt + 1)
    return out


class GroupedDataset:
    """(reference: python/ray/data/grouped_data.py GroupedData)"""

    def __init__(self, ds: "Dataset", key: str):
        self._ds = ds
        self._key = key

    def _run(self, col: Optional[str], kind: str) -> "Dataset":
        partials = ray_tpu.get([
            _part_group_agg.remote(src, ops, self._key, col, kind)
            for src, ops in self._ds._plan_parts()
        ])
        merged: dict = {}
        for part in partials:
            for k, (acc, cnt) in part.items():
                macc, mcnt = merged.get(k, (None, 0))
                if acc is None or macc is None:
                    macc = acc if macc is None else macc
                elif kind in ("sum", "mean"):
                    macc = macc + acc
                elif kind == "min":
                    macc = min(macc, acc)
                elif kind == "max":
                    macc = max(macc, acc)
                merged[k] = (macc, mcnt + cnt)
        out_col = f"{kind}({col})" if col else "count()"
        rows = []
        for k in sorted(merged):
            acc, cnt = merged[k]
            if kind == "count":
                val = cnt
            elif kind == "mean":
                val = acc / cnt if cnt else None
            else:
                val = acc
            rows.append({self._key: k, out_col: val})
        return from_items(rows)

    def count(self) -> "Dataset":
        return self._run(None, "count")

    def sum(self, col: str) -> "Dataset":
        return self._run(col, "sum")

    def mean(self, col: str) -> "Dataset":
        return self._run(col, "mean")

    def min(self, col: str) -> "Dataset":
        return self._run(col, "min")

    def max(self, col: str) -> "Dataset":
        return self._run(col, "max")

    def map_groups(self, fn: Callable[[Batch], Batch]) -> "Dataset":
        """Apply ``fn`` to each group's batch (reference: grouped_data.py
        map_groups — sorts by key, then applies the UDF per contiguous
        group).  Single-task application after the sort; fine at the same
        scale as Dataset.sort."""
        key = self._key
        sorted_ds = self._ds.sort(key)
        refs, _ = sorted_ds._materialize_refs()

        @ray_tpu.remote
        def apply(refs: List[Any]) -> Block:
            block = Block.concat([ray_tpu.get(r) for r in refs])
            cols = block.to_numpy()
            keys = cols[key]
            pieces = []
            lo = 0
            for hi in builtins.range(1, len(keys) + 1):
                if hi == len(keys) or keys[hi] != keys[lo]:
                    group = {k: v[lo:hi] for k, v in cols.items()}
                    out = fn(group)
                    pieces.append(Block.from_batch(out))
                    lo = hi
            return Block.concat(pieces) if pieces else Block.from_batch({})

        return Dataset([(apply.remote(refs), [])])


@ray_tpu.remote
def _sample_column(block: Block, key: str, k: int) -> np.ndarray:
    """Up to k evenly-spaced sample values of one block's sort column
    (block ref resolves at the task boundary; reference:
    planner/exchange/sort_task_spec.py sample_boundaries)."""
    arr = block.to_numpy()[key]
    if len(arr) <= k:
        return np.asarray(arr)
    idx = np.linspace(0, len(arr) - 1, k).astype(np.int64)
    return np.asarray(arr)[idx]


@ray_tpu.remote
def _range_partition(block: Block, key: str, bounds: List) -> List[Block]:
    """Split one block into len(bounds)+1 sub-blocks by sort-key range
    (submitted with num_returns so each range lands in its own object)."""
    arr = np.asarray(block.to_numpy()[key])
    which = np.searchsorted(np.asarray(bounds), arr, side="right")
    return [block.take_rows(np.flatnonzero(which == j))
            for j in builtins.range(len(bounds) + 1)]


@ray_tpu.remote
def _sort_range(refs: List[Any], key: str, descending: bool) -> Block:
    """Concat one range's partitions from every input block and sort it —
    each output task holds only its range, never the whole dataset."""
    block = Block.concat([ray_tpu.get(r) for r in refs])
    if block.num_rows == 0:
        return block  # a range can be empty (all-duplicate sample bounds)
    order = np.argsort(np.asarray(block.to_numpy()[key]), kind="stable")
    if descending:
        order = order[::-1]
    return block.take_rows(order)


@ray_tpu.remote
def _shuffle_partition(block: Block, n_out: int, seed) -> List[Block]:
    """Assign each row of one block to a uniformly random output bucket
    (submitted with num_returns=n_out)."""
    rng = np.random.default_rng(seed)
    which = rng.integers(0, n_out, block.num_rows)
    return [block.take_rows(np.flatnonzero(which == j))
            for j in builtins.range(n_out)]


@ray_tpu.remote
def _shuffle_merge(refs: List[Any], seed) -> Block:
    """Concat one output bucket's pieces and permute rows locally."""
    block = Block.concat([ray_tpu.get(r) for r in refs])
    rng = np.random.default_rng(seed)
    return block.take_rows(rng.permutation(block.num_rows))


def _join_key_digestable(v) -> str:
    """Canonical string for partition routing.  Values the probe-side dict
    treats as EQUAL (python equality: 2 == 2.0) must digest identically,
    or the same join returns different rows at different partition counts;
    and hash() itself is salted per worker process, so a digest of this
    canonical form is the only stable router."""
    if hasattr(v, "item"):
        v = v.item()
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return repr(v)
    try:
        f = float(v)
    except OverflowError:  # int beyond float range: no float equals it
        return repr(v)
    if f != v:
        return repr(v)  # int not exactly representable: no float equals it
    if abs(f) < 2.0 ** 53:  # exactly representable: canonical
        return repr(f)
    # |v| >= 2**53: repr(float) and repr(int) diverge for EQUAL values
    # (1 << 53 vs 9.007199254740992e+15) — integer-valued keys share the
    # exact integer form so int and float keys that compare equal route
    # to the same partition.  All floats this large are integers.
    if isinstance(v, int):
        return repr(v)
    return repr(int(f)) if f.is_integer() else repr(f)


@ray_tpu.remote
def _hash_partition(block: Block, key: str, n_out: int) -> List[Block]:
    """Route each row to digest(key) % n_out (submitted with
    num_returns=n_out) — stage 1 of the join exchange."""
    keys = block.to_numpy()[key]
    import zlib

    which = np.fromiter(
        (zlib.crc32(_join_key_digestable(v).encode()) % n_out
         for v in keys),
        dtype=np.int64, count=len(keys),
    )
    return [block.take_rows(np.flatnonzero(which == j))
            for j in builtins.range(n_out)]


@ray_tpu.remote
def _np_schema(refs: List[Any]) -> Dict[str, str]:
    """Raw numpy dtype strings (np.dtype-parseable) of the first non-empty
    block — the join exchange ships these so empty partitions keep the
    full column set."""
    for r in refs:
        b = ray_tpu.get(r)
        if b.num_rows:
            return {k: v.dtype.str for k, v in b.to_numpy().items()}
    return {}


@ray_tpu.remote
def _hash_join_partition(left_refs: List[Any], right_refs: List[Any],
                         on: str, how: str, suffix: str,
                         lschema: Dict[str, str],
                         rschema: Dict[str, str]) -> Block:
    """Stage 2: join ONE hash partition.  Build an index over the right
    side's keys, probe with the left side's (classic hash join; both
    sides of a partition share hash(key), so the join is complete).
    ``lschema``/``rschema`` carry the full column sets so partitions with
    an empty side still emit schema-consistent blocks (a left join whose
    partition has no right rows must still create the right columns)."""
    left = Block.concat([ray_tpu.get(r) for r in left_refs])
    right = Block.concat([ray_tpu.get(r) for r in right_refs])
    lcols = left.to_numpy()
    rcols = right.to_numpy()
    for name, dt in lschema.items():
        if name not in lcols:
            lcols[name] = np.empty(0, np.dtype(dt))
    for name, dt in rschema.items():
        if name not in rcols:
            rcols[name] = np.empty(0, np.dtype(dt))
    rkeys = rcols.get(on, np.array([]))
    index: dict = {}
    for i in builtins.range(len(rkeys)):
        k = rkeys[i].item() if hasattr(rkeys[i], "item") else rkeys[i]
        index.setdefault(k, []).append(i)
    lkeys = lcols.get(on, np.array([]))
    li: List[int] = []
    ri: List[int] = []
    unmatched: List[int] = []
    for i in builtins.range(len(lkeys)):
        k = lkeys[i].item() if hasattr(lkeys[i], "item") else lkeys[i]
        rows = index.get(k)
        if rows:
            li.extend([i] * len(rows))
            ri.extend(rows)
        elif how == "left":
            unmatched.append(i)
    out: Dict[str, np.ndarray] = {}
    li_a, ri_a = np.asarray(li, np.int64), np.asarray(ri, np.int64)
    for name, col in lcols.items():
        out[name] = col[li_a]
    for name, col in rcols.items():
        if name == on:
            continue
        out_name = name + suffix if name in lcols else name
        out[out_name] = col[ri_a]
    if how == "left":
        # Nullable right columns upcast UNCONDITIONALLY (numeric->float64,
        # else object): per-partition upcasting-only-when-unmatched would
        # give the same output column different dtypes in different
        # partitions.
        for name, col in rcols.items():
            if name == on:
                continue
            out_name = name + suffix if name in lcols else name
            if np.issubdtype(col.dtype, np.number):
                out[out_name] = out[out_name].astype(np.float64,
                                                     copy=False)
            else:
                out[out_name] = out[out_name].astype(object, copy=False)
        if unmatched:
            um = np.asarray(unmatched, np.int64)
            for name, col in lcols.items():
                out[name] = np.concatenate([out[name], col[um]])
            n_um = len(um)
            for name, col in rcols.items():
                if name == on:
                    continue
                out_name = name + suffix if name in lcols else name
                fill = (np.full(n_um, np.nan)
                        if np.issubdtype(col.dtype, np.number)
                        else np.full(n_um, None, object))
                out[out_name] = np.concatenate([out[out_name], fill])
    return Block.from_batch(out) if out else Block({})


@ray_tpu.remote
def _gather_spans(spans: List[tuple]) -> Block:
    """Concatenate row spans [(block_ref, lo, hi), ...] into one block.
    Workers pull the referenced blocks (cross-node via the object plane)."""
    pieces = []
    for ref, lo, hi in spans:
        block = ray_tpu.get(ref)
        pieces.append(block.slice(lo, hi))
    return Block.concat(pieces)


@ray_tpu.remote
def _gather_indices(parts: List[tuple]) -> Block:
    """Concatenate fancy-indexed selections [(block_ref, indices), ...]."""
    pieces = []
    for ref, idx in parts:
        block = ray_tpu.get(ref)
        pieces.append(block.take_rows(np.asarray(idx)))
    return Block.concat(pieces)


@ray_tpu.remote
def _write_parquet_task(source: Source, ops: List[Op], path: str) -> int:
    import pyarrow.parquet as pq

    block = source() if callable(source) else source
    for op in ops:
        block = op(block)
    pq.write_table(block.to_arrow(), path)
    return block.num_rows


@ray_tpu.remote
def _write_csv_task(source: Source, ops: List[Op], path: str) -> int:
    import pyarrow.csv as pacsv

    block = source() if callable(source) else source
    for op in ops:
        block = op(block)
    pacsv.write_csv(block.to_arrow(), path)
    return block.num_rows


@ray_tpu.remote
def _write_json_task(source: Source, ops: List[Op], path: str) -> int:
    """JSON-lines, one object per row (reference: data write_json emits
    pandas-style JSONL files)."""
    import json as _json

    block = source() if callable(source) else source
    for op in ops:
        block = op(block)
    def cell(v):
        if isinstance(v, np.ndarray):
            return v.tolist()  # tensor column: serialize as a nested list
        return v.item() if hasattr(v, "item") else v

    cols = block.to_numpy()
    names = list(cols)
    with open(path, "w") as f:
        for i in builtins.range(block.num_rows):
            f.write(_json.dumps({k: cell(cols[k][i]) for k in names}) + "\n")
    return block.num_rows


@ray_tpu.remote
def _zip_spans(left_spans: List[tuple], right_spans: List[tuple]) -> Block:
    """Column-wise join of two row-aligned span lists.  Duplicate column
    names from the right side get a _1 suffix (reference: dataset.py zip
    disambiguates with suffixes)."""
    def gather(spans):
        return Block.concat([
            ray_tpu.get(r).slice(lo, hi) for r, lo, hi in spans
        ])

    left, right = gather(left_spans), gather(right_spans)
    lcols, rcols = left.to_numpy(), right.to_numpy()
    out = dict(lcols)
    for k, v in rcols.items():
        name, i = k, 1
        while name in out:  # probe _1, _2, ... until free — never overwrite
            name = f"{k}_{i}"
            i += 1
        out[name] = v
    return Block.from_batch(out)


class ActorPoolStrategy:
    """Compute strategy for stateful map_batches UDFs: a fixed pool of
    actors each instantiating the UDF class once and reusing it across
    blocks (reference: data/_internal/execution/operators/
    actor_pool_map_operator.py — essential for accelerator-resident or
    expensive-to-construct preprocessing state)."""

    def __init__(self, size: int = 2, *, num_cpus: float = 1.0,
                 max_tasks_in_flight_per_actor: int = 2):
        assert size >= 1
        self.size = size
        self.num_cpus = num_cpus
        self.max_tasks_in_flight_per_actor = max_tasks_in_flight_per_actor


# Worker/actor-process-global cache of stateful UDF instances, keyed by the
# op's uid: one instance per op per actor process, living as long as the
# pool actor does (the reference's _MapWorker holds the callable the same
# way).
_UDF_INSTANCES: Dict[str, Any] = {}


class _StatefulBatchOp:
    """A picklable op wrapping a callable-class UDF.  Executed inside a pool
    actor; the instance is constructed on first use and cached process-wide
    under the op uid."""

    def __init__(self, fn_cls, ctor_args, ctor_kwargs, batch_format: str,
                 fn_kwargs: Optional[dict], pool: ActorPoolStrategy):
        import uuid as _uuid

        self.fn_cls = fn_cls
        self.ctor_args = tuple(ctor_args or ())
        self.ctor_kwargs = dict(ctor_kwargs or {})
        self.batch_format = batch_format
        self.fn_kwargs = fn_kwargs or {}
        self.pool = pool  # executor routes chains containing this op
        self.uid = _uuid.uuid4().hex

    def __call__(self, block: Block) -> Block:
        inst = _UDF_INSTANCES.get(self.uid)
        if inst is None:
            inst = _UDF_INSTANCES[self.uid] = self.fn_cls(
                *self.ctor_args, **self.ctor_kwargs
            )
        return _apply_batch_fn(inst, block, self.batch_format,
                               self.fn_kwargs)


@ray_tpu.remote
class _PoolWorker:
    """One actor of an ActorPoolStrategy pool: executes whole part chains
    so stateful ops hit this process's UDF instance cache."""

    def exec_part(self, source: Source, ops: List[Op]) -> Block:
        return _exec_part_body(source, ops)

    def ping(self) -> bool:
        return True


class _PoolManager:
    """Per-execution actor pools: created lazily on first routed chain,
    round-robin dispatch, torn down after every routed task completed
    (killing earlier would kill queued tasks)."""

    def __init__(self):
        self._pools: Dict[int, List[Any]] = {}
        self._rr: Dict[int, int] = {}
        self._routed_refs: List[Any] = []

    @staticmethod
    def pool_of(ops: List[Op]) -> Optional[ActorPoolStrategy]:
        for op in ops:
            pool = getattr(op, "pool", None)
            if pool is not None:
                return pool
        return None

    def submit(self, source: Source, ops: List[Op],
               pool: ActorPoolStrategy):
        key = id(pool)
        actors = self._pools.get(key)
        if actors is None:
            actors = self._pools[key] = [
                _PoolWorker.options(num_cpus=pool.num_cpus).remote()
                for _ in builtins.range(pool.size)
            ]
            self._rr[key] = 0
        i = self._rr[key]
        self._rr[key] = (i + 1) % len(actors)
        ref = actors[i].exec_part.remote(source, ops)
        # Track only still-running work (so shutdown won't kill actors with
        # queued tasks) and prune completed refs eagerly: holding a ref pins
        # the block in the store, which would defeat backpressure.
        if self._routed_refs:
            ready, _ = ray_tpu.wait(self._routed_refs,
                                    num_returns=len(self._routed_refs),
                                    timeout=0)
            done = set(r.binary() for r in ready)
            self._routed_refs = [r for r in self._routed_refs
                                 if r.binary() not in done]
        self._routed_refs.append(ref)
        return ref

    def shutdown(self):
        if not self._pools:
            return
        try:
            # Wait until every routed task completed before killing its
            # actor.  No hard deadline — a slow UDF keeps its pool alive —
            # but a stall (no completions for 600s straight) aborts.
            while self._routed_refs:
                n_before = len(self._routed_refs)
                ready, rest = ray_tpu.wait(
                    self._routed_refs, num_returns=n_before, timeout=600
                )
                self._routed_refs = list(rest)
                if self._routed_refs and len(ready) == 0:
                    import sys as _sys

                    print("ray_tpu.data: pool shutdown stalled 600s with "
                          f"{len(self._routed_refs)} tasks in flight; "
                          "killing pool actors", file=_sys.stderr)
                    break
        finally:
            for actors in self._pools.values():
                for a in actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass
            self._pools.clear()
            self._routed_refs = []


def _object_sizes(refs: List[Any]) -> List[Optional[int]]:
    """Sealed sizes (None while running) via the head's object table."""
    from ray_tpu.core.context import ctx

    try:
        reply = ctx.client.call(
            "object_sizes",
            {"object_ids": [r.binary() for r in refs]},
        )
        return reply["sizes"]
    except Exception:
        return [None] * len(refs)


def _apply_batch_fn(fn, block: Block, batch_format: str,
                    kwargs: dict) -> Block:
    if batch_format == "numpy":
        out = fn(block.to_numpy(), **kwargs)
    elif batch_format == "pandas":
        out = fn(block.to_pandas(), **kwargs)
    elif batch_format == "pyarrow":
        out = fn(block.to_arrow(), **kwargs)
    else:
        raise ValueError(f"unknown batch_format {batch_format!r}")
    return _coerce_batch_out(out)


def _coerce_batch_out(out) -> Block:
    if isinstance(out, Block):
        return out
    if isinstance(out, dict):
        return Block.from_batch(out)
    try:
        import pandas as pd

        if isinstance(out, pd.DataFrame):
            return Block.from_batch(
                {c: out[c].to_numpy() for c in out.columns}
            )
    except ImportError:
        pass
    import pyarrow as pa

    if isinstance(out, pa.Table):
        return Block.from_arrow(out)
    raise TypeError(
        f"map_batches fn must return dict/DataFrame/Table, got {type(out)}"
    )


def _batch_op(fn, batch_format: str, fn_kwargs: Optional[dict]) -> Op:
    kwargs = fn_kwargs or {}

    def op(block: Block) -> Block:
        return _apply_batch_fn(fn, block, batch_format, kwargs)

    return op


class Dataset:
    """Lazy, immutable dataset of blocks distributed over the cluster."""

    def __init__(self, parts: List[tuple],
                 counts: Optional[List[int]] = None,
                 total_rows: Optional[int] = None,
                 logical=None):
        self._parts = parts  # [(source, [op, ...]), ...]
        self._counts = counts  # per-part row counts, when known
        # Total row count when per-part counts are unknown but the total is
        # invariant (sort/shuffle exchanges preserve it).
        self._total_rows = (sum(counts) if counts is not None
                            else total_rows)
        # The inspectable plan description (reference: logical_plan.py);
        # optimize() fires fusion/pushdown rules over it (logical.py).
        self._logical = logical if logical is not None else LogicalPlan()
        # Per-operator rows from the last materialize() of/into this
        # dataset (None until then) — lets stats() report that run
        # instead of re-executing the plan.
        self._materialized_stats: Optional[List[Dict[str, Any]]] = None

    # ---------------------------------------------------------- transforms

    def _with_op(self, op: Op, lop=None) -> "Dataset":
        if lop is None:
            lop = LogicalOp("map", _op_name(op))
        return Dataset([(src, ops + [op]) for src, ops in self._parts],
                       logical=self._logical.appended(lop))

    def _plan_parts(self) -> List[tuple]:
        """Parts safe for direct stateless-task submission.  A chain with an
        ActorPoolStrategy op must run on its pool (instance reuse, sizing),
        so such plans materialize through the pool-routed executor first."""
        if any(_PoolManager.pool_of(ops) is not None
               for _, ops in self._parts):
            return self.materialize()._parts
        return self._parts

    def map_batches(
        self,
        fn: Callable[..., Union[Batch, Any]],
        *,
        batch_format: str = "numpy",
        fn_kwargs: Optional[dict] = None,
        compute: Optional[ActorPoolStrategy] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        batch_size: Optional[int] = None,  # accepted for API parity; the
        # whole block is one batch (tasks already bound block sizes)
    ) -> "Dataset":
        """Apply fn to batches (reference: dataset.py map_batches:383).

        With ``compute=ActorPoolStrategy(size=n)`` and a callable-class
        ``fn``, each pool actor constructs the UDF once and reuses it
        across blocks — the stateful-UDF path (reference:
        actor_pool_map_operator.py)."""
        if compute is not None:
            if not isinstance(fn, type):
                raise TypeError(
                    "compute=ActorPoolStrategy requires a callable CLASS "
                    "(constructed once per pool actor); got "
                    f"{type(fn).__name__}"
                )
            sop = _StatefulBatchOp(
                fn, fn_constructor_args, fn_constructor_kwargs,
                batch_format, fn_kwargs, compute,
            )
            return self._with_op(sop, LogicalOp(
                "map_batches", _op_name(sop),
                {"compute": f"ActorPool({compute.size})"}))
        if isinstance(fn, type):
            # Task path: one driver-side instance shipped to tasks.
            fname = fn.__name__
            fn = fn(*fn_constructor_args, **(fn_constructor_kwargs or {}))
        else:
            fname = getattr(fn, "__name__", type(fn).__name__)
        return self._with_op(
            _TimedOp(f"MapBatches({fname})",
                     _batch_op(fn, batch_format, fn_kwargs)),
            LogicalOp("map_batches", f"MapBatches({fname})"))

    def map(self, fn: Callable[[Dict], Dict]) -> "Dataset":
        def op(block: Block) -> Block:
            return Block.from_items([fn(row) for row in block.rows()])

        name = f"Map({getattr(fn, '__name__', 'fn')})"
        return self._with_op(_TimedOp(name, op), LogicalOp("map", name))

    def flat_map(self, fn: Callable[[Dict], List[Dict]]) -> "Dataset":
        def op(block: Block) -> Block:
            rows: List[Dict] = []
            for row in block.rows():
                rows.extend(fn(row))
            return Block.from_items(rows) if rows else Block({})

        name = f"FlatMap({getattr(fn, '__name__', 'fn')})"
        return self._with_op(_TimedOp(name, op),
                             LogicalOp("flat_map", name))

    def filter(self, fn: Callable[[Dict], bool]) -> "Dataset":
        def op(block: Block) -> Block:
            batch = block.to_numpy()
            keep = np.fromiter(
                (bool(fn(row)) for row in block.rows()), dtype=bool,
                count=block.num_rows,
            )
            return Block({k: v[keep] for k, v in batch.items()})

        name = f"Filter({getattr(fn, '__name__', 'fn')})"
        return self._with_op(_TimedOp(name, op), LogicalOp("filter", name))

    def _try_read_pushdown(self, **updates) -> Optional["Dataset"]:
        """Fold a projection/limit into the read sources when every part is
        a bare pushdown-capable _ReadTask (reference: the logical rules in
        logical/rules/ rewrite Read ops the same way).  Returns the new
        parts list, or None when pushdown does not apply."""
        if not self._parts:
            return None
        for src, ops in self._parts:
            if ops or not isinstance(src, _ReadTask):
                return None
            if ("columns" in updates
                    and src.kind not in _ReadTask.SUPPORTS_COLUMNS):
                return None
            if "columns" in updates and src.columns is not None:
                return None  # already pruned: chain the op instead
        new_parts = []
        for src, _ in self._parts:
            ns = _ReadTask(src.kind, src.files,
                           updates.get("columns", src.columns),
                           updates.get("limit", src.limit),
                           src.reader_kwargs)
            new_parts.append((ns, []))
        return new_parts

    def select_columns(self, columns: Sequence[str]) -> "Dataset":
        cols = list(columns)
        pushed = self._try_read_pushdown(columns=cols)
        if pushed is not None:
            # Column pruning folds into the parquet read itself: pruned
            # columns are never decoded, and the logical plan records the
            # rewritten Read (the optimizer's ReadPushdown rule output).
            lop = LogicalOp("project", "Project", {"columns": cols})
            return Dataset(pushed, self._counts, self._total_rows,
                           logical=self._logical.appended(lop))
        return self._with_op(
            _TimedOp("Project", lambda b: b.select(cols)),
            LogicalOp("project", "Project", {"columns": cols}))

    def add_column(self, name: str, fn: Callable[[Batch], np.ndarray]) -> "Dataset":
        def op(block: Block) -> Block:
            batch = block.to_numpy()
            batch[name] = np.asarray(fn(batch))
            return Block.from_batch(batch)

        return self._with_op(_TimedOp(f"AddColumn({name})", op),
                             LogicalOp("add_column", f"AddColumn({name})"))

    def drop_columns(self, columns: Sequence[str]) -> "Dataset":
        drop = set(columns)

        def op(block: Block) -> Block:
            return block.select([c for c in block.columns() if c not in drop])

        return self._with_op(
            _TimedOp("DropColumns", op),
            LogicalOp("drop_column", "DropColumns",
                      {"columns": sorted(drop)}))

    # ------------------------------------------------------- reorganization

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance into exactly num_blocks equal-ish blocks (reference:
        dataset.py repartition:1042).  Materializes, then one gather task
        per output block pulls just the row spans it needs."""
        refs, counts = self._materialize_refs()
        total = sum(counts)
        bounds = [total * i // num_blocks for i in builtins.range(num_blocks + 1)]
        # Prefix sums map global row ranges onto (block, local range) spans.
        starts = np.cumsum([0] + counts)
        parts: List[tuple] = []
        out_counts: List[int] = []
        for j in builtins.range(num_blocks):
            lo, hi = bounds[j], bounds[j + 1]
            spans = []
            for i, ref in enumerate(refs):
                blo, bhi = starts[i], starts[i + 1]
                s, e = max(lo, blo), min(hi, bhi)
                if s < e:
                    spans.append((ref, int(s - blo), int(e - blo)))
            parts.append((_gather_spans.remote(spans), []))
            out_counts.append(hi - lo)
        return Dataset(parts, out_counts)

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Global row shuffle as a two-stage partition/merge exchange
        (reference: all-to-all shuffle in
        data/_internal/planner/exchange/shuffle_task_spec.py).  Stage 1:
        one task per input block assigns each row to a uniformly random
        output bucket (num_returns fan-out — no driver-side permutation,
        no global gather).  Stage 2: one task per output block concats its
        bucket from every input and permutes locally.  Peak task state is
        one block, so this survives datasets no single worker could hold."""
        refs, counts = self._materialize_refs()
        n_out = max(len(refs), 1)
        if seed is not None:
            base = seed
        else:
            import os as _os

            base = int.from_bytes(_os.urandom(8), "little")
        if n_out == 1:
            return Dataset(
                [(_shuffle_merge.remote(refs, (base, 1, 0)), [])],
                [sum(counts)],
            )
        part_lists = [
            _shuffle_partition.options(num_returns=n_out).remote(
                ref, n_out, (base, 0, i))
            for i, ref in enumerate(refs)
        ]
        parts: List[tuple] = []
        for j in builtins.range(n_out):
            bucket = [pl[j] for pl in part_lists]
            parts.append((_shuffle_merge.remote(bucket, (base, 1, j)), []))
        return Dataset(parts, total_rows=sum(counts))

    def join(self, other: "Dataset", on: str, how: str = "inner",
             *, num_partitions: Optional[int] = None,
             suffix: str = "_r") -> "Dataset":
        """Key-based join as a hash-partition/merge exchange (reference:
        Dataset.join — distributed hash join; the exchange shape matches
        planner/exchange/: stage 1 hash-routes each input block's rows
        with a num_returns fan-out, stage 2 joins one partition per task,
        so no worker ever holds either full dataset).

        ``how``: "inner" or "left".  Right-side columns colliding with
        left names (other than the key) get ``suffix``.  Key hashing uses
        a content digest, not Python hash() (which is salted per worker
        process)."""
        if how not in ("inner", "left"):
            raise ValueError(f"unsupported join how={how!r}")
        left_refs = list(self._iter_block_refs())
        right_refs = list(other._iter_block_refs())
        n_out = num_partitions or max(len(left_refs), len(right_refs), 1)
        lschema_ref = _np_schema.remote(left_refs)
        rschema_ref = _np_schema.remote(right_refs)

        def scatter(refs):
            if n_out == 1:
                return [list(refs)]
            lists = [
                _hash_partition.options(num_returns=n_out).remote(
                    r, on, n_out)
                for r in refs
            ]
            return [[pl[j] for pl in lists]
                    for j in builtins.range(n_out)]

        left_parts = scatter(left_refs)
        right_parts = scatter(right_refs)
        lschema, rschema = ray_tpu.get([lschema_ref, rschema_ref])
        parts = [
            (_hash_join_partition.remote(
                left_parts[j], right_parts[j], on, how, suffix,
                lschema, rschema), [])
            for j in builtins.range(n_out)
        ]
        return Dataset(
            parts,
            logical=self._logical.appended(LogicalOp(
                "exchange", f"HashJoin[{how}]",
                {"on": on, "partitions": n_out})),
        )

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Total order by one column via a sample -> range-partition ->
        per-range sort exchange (reference:
        planner/exchange/sort_task_spec.py): boundary values come from
        per-block samples, every input block splits itself into ranges
        (num_returns fan-out), and each output task sorts ONE range — no
        task ever holds the whole dataset."""
        refs, counts = self._materialize_refs()
        n_out = len(refs)
        if n_out <= 1:
            return Dataset(
                [(_sort_range.remote(refs, key, descending), [])],
                [sum(counts)],
            )
        samples = ray_tpu.get(
            [_sample_column.remote(r, key, 32) for r in refs]
        )
        allsamp = np.sort(np.concatenate(
            [s for s in samples if len(s)] or [np.empty(0)]
        ))
        if len(allsamp) == 0:
            return Dataset(
                [(_sort_range.remote(refs, key, descending), [])],
                [sum(counts)],
            )
        bounds = [
            allsamp[len(allsamp) * j // n_out]
            for j in builtins.range(1, n_out)
        ]
        part_lists = [
            _range_partition.options(num_returns=n_out).remote(
                r, key, bounds)
            for r in refs
        ]
        order = builtins.range(n_out)
        if descending:
            order = reversed(order)  # highest range first
        parts = [
            (_sort_range.remote([pl[j] for pl in part_lists], key,
                                descending), [])
            for j in order
        ]
        return Dataset(parts, total_rows=sum(counts))

    def union(self, other: "Dataset") -> "Dataset":
        return Dataset(self._parts + other._parts)

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise join of two row-aligned datasets (reference:
        dataset.py zip — counts must match; right-side duplicate column
        names get a _1 suffix).  Output partitioning follows self's blocks;
        right spans covering each left block are gathered per task."""
        lrefs, lcounts = self._materialize_refs()
        rrefs, rcounts = other._materialize_refs()
        if sum(lcounts) != sum(rcounts):
            raise ValueError(
                f"zip requires equal row counts "
                f"({sum(lcounts)} != {sum(rcounts)})"
            )

        def spans_for(lo: int, hi: int) -> List[tuple]:
            """Right-side spans covering global rows [lo, hi)."""
            out, pos = [], 0
            for ref, cnt in builtins.zip(rrefs, rcounts):
                start, end = pos, pos + cnt
                pos = end
                if end <= lo or start >= hi:
                    continue
                out.append((ref, max(lo - start, 0), min(hi, end) - start))
            return out

        parts, pos = [], 0
        for ref, cnt in builtins.zip(lrefs, lcounts):
            parts.append((
                _zip_spans.remote([(ref, 0, cnt)], spans_for(pos, pos + cnt)),
                [],
            ))
            pos += cnt
        return Dataset(parts, list(lcounts))

    def random_sample(self, fraction: float,
                      seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: dataset.py random_sample).
        Seeded runs are reproducible for the same dataset; unseeded runs
        draw a fresh base seed per call."""
        if seed is not None:
            base = seed
        else:
            import os as _os

            base = int.from_bytes(_os.urandom(8), "little")

        def op(block: Block) -> Block:
            import zlib

            n = block.num_rows
            if n == 0:
                return block
            # Distinct stream per block: fold in a content fingerprint
            # (first/last row of the first column) so equal-sized blocks
            # don't replay identical in-block positions.
            cols = block.to_numpy()
            fp = 0
            if cols:
                first = next(iter(cols.values()))
                fp = zlib.crc32(
                    np.ascontiguousarray(first[:1]).tobytes()
                    + np.ascontiguousarray(first[-1:]).tobytes()
                )
            rng = np.random.default_rng((base, n, fp))
            return block.take_rows(np.flatnonzero(rng.random(n) < fraction))

        return self._with_op(op)

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column, computed as per-block partials on
        the cluster (reference: dataset.py unique — only each block's
        distinct set travels to the driver)."""
        partials = [p for p in ray_tpu.get(
            [_part_agg.remote(src, ops, column, "unique")
             for src, ops in self._plan_parts()]
        ) if p is not None]
        seen: set = set()
        for vals, _ in partials:
            seen.update(vals)
        return sorted(seen)

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None) -> List["Dataset"]:
        """Split into (train, test) datasets (reference: dataset.py
        train_test_split)."""
        if not 0 < test_size < 1:
            raise ValueError("test_size must be in (0, 1)")
        ds = self.random_shuffle(seed=seed) if shuffle else self
        refs, counts = ds._materialize_refs()
        total = sum(counts)
        n_test = int(total * test_size)
        n_train = total - n_test
        train = Dataset([(r, []) for r in refs], counts).limit(n_train)
        # Tail rows: skip n_train, keep the rest.
        tail_parts, pos = [], 0
        tail_counts = []
        for ref, cnt in builtins.zip(refs, counts):
            start, end = pos, pos + cnt
            pos = end
            if end <= n_train:
                continue
            lo = max(n_train - start, 0)
            tail_parts.append((_gather_spans.remote([(ref, lo, cnt)]), []))
            tail_counts.append(cnt - lo)
        return [train, Dataset(tail_parts, tail_counts)]

    def limit(self, k: int) -> "Dataset":
        """First k rows (streams only as many parts as needed).  On a bare
        file-read plan the limit pushes into the read itself first (each
        part stops opening files once it has k rows), so a limit over a
        large dataset never materializes whole blocks."""
        lop = LogicalOp("limit", "Limit", {"n": k})
        src_ds = self
        pushed = self._try_read_pushdown(limit=k)
        if pushed is not None:
            src_ds = Dataset(pushed, logical=self._logical.appended(lop))
        else:
            src_ds = Dataset(self._parts, self._counts, self._total_rows,
                             logical=self._logical.appended(lop))
        taken: List[tuple] = []
        counts: List[int] = []
        remaining = k
        for ref in src_ds._iter_block_refs():
            if remaining <= 0:
                break
            block = ray_tpu.get(ref)
            n = block.num_rows
            if n <= remaining:
                taken.append((ref, []))
                counts.append(n)
                remaining -= n
            else:
                taken.append((ray_tpu.put(block.slice(0, remaining)), []))
                counts.append(remaining)
                remaining = 0
        return Dataset(taken, counts, logical=src_ds._logical)

    # ------------------------------------------------------------ execution

    def _iter_block_refs(self, window: Optional[int] = None,
                         timed_sink: Optional[List] = None) -> Iterator[Any]:
        """Launch part tasks with a bounded in-flight window, yielding block
        refs in plan order (the pull-based streaming executor: the consumer's
        pace bounds cluster work — reference: streaming_executor.py:48).

        Backpressure is two-dimensional (reference:
        execution/backpressure_policy/ + resource_manager.py):
        - task count: never more than ``execution_window`` parts in flight;
        - bytes: the window adapts down to keep (in-flight blocks x learned
          block size) under ``DataContext.max_in_flight_bytes``.  Sizing
          uses a HIGH PERCENTILE (p90) of recently observed block sizes,
          not the mean — a mixed dataset (small metadata blocks, then
          large image blocks) would overshoot the budget several-fold
          while a mean caught up.  Sizes come from sealed objects via the
          head's object table (no fetches), probed every submission until
          the sample is warm.
        Chains containing an ActorPoolStrategy op route to that pool's
        actors instead of stateless tasks."""
        cfg = DataContext.get_current()
        max_win = window or cfg.execution_window
        budget = cfg.max_in_flight_bytes
        min_win = max(1, cfg.min_execution_window)
        stats = {"peak_in_flight": 0, "submitted": 0,
                 "effective_window_min": max_win}
        cfg.last_execution_stats = stats
        pools = _PoolManager()
        seen_ids: set = set()
        recent_sizes: deque = deque(maxlen=64)  # sliding sample window
        warm_after = 8
        try:
            pending: deque = deque()
            for src, ops in self._parts:
                eff = max_win
                if budget and recent_sizes:
                    ordered = sorted(recent_sizes)
                    # Nearest-rank p90 (rounds toward the max for small
                    # samples — conservative means under-budget, never
                    # over).
                    p90 = ordered[min(len(ordered) - 1,
                                      int(0.9 * len(ordered)))]
                    if p90 > 0:
                        eff = max(min_win,
                                  min(max_win, int(budget // p90)))
                stats["effective_window_min"] = min(
                    stats["effective_window_min"], eff)
                while len(pending) >= eff:
                    yield pending.popleft()
                pool = _PoolManager.pool_of(ops)
                if pool is not None:
                    ref = pools.submit(src, ops, pool)
                elif not ops and not callable(src):
                    ref = src  # already-materialized block: no task needed
                elif timed_sink is not None:
                    # Opportunistic per-operator timing (materialize):
                    # same chain, block + timing rows as two returns.
                    # Pool-routed and pre-materialized parts above carry
                    # no timings (documented in stats()).
                    ref, t_ref = _exec_part_timed.options(
                        num_returns=2).remote(src, ops)
                    timed_sink.append(t_ref)
                else:
                    ref = _exec_part.remote(src, ops)
                pending.append(ref)
                stats["submitted"] += 1
                stats["peak_in_flight"] = max(stats["peak_in_flight"],
                                              len(pending))
                # Probe every submission until the sample is warm (a cold
                # mean/percentile is what lets mixed sizes overshoot),
                # then every 4th.
                if budget and (len(recent_sizes) < warm_after
                               or stats["submitted"] % 4 == 0):
                    probe = [r for r in pending
                             if r.binary() not in seen_ids]
                    if probe:
                        # Key by id bytes, NOT the ref: holding refs here
                        # would pin every probed block in the store.
                        for r, sz in zip(probe, _object_sizes(probe)):
                            if sz:
                                seen_ids.add(r.binary())
                                recent_sizes.append(sz)
            while pending:
                yield pending.popleft()
        finally:
            pools.shutdown()

    def iter_blocks(self) -> Iterator[Block]:
        for ref in self._iter_block_refs():
            yield ray_tpu.get(ref)

    def _materialize_refs(self, timed_sink: Optional[List] = None) -> tuple:
        refs = list(self._iter_block_refs(timed_sink=timed_sink))
        if self._counts is not None and builtins.all(
            not ops and not callable(src) for src, ops in self._parts
        ):
            return refs, list(self._counts)
        counts = ray_tpu.get(
            [_part_rows.remote(ref, []) for ref in refs]
        )
        return refs, counts

    def materialize(self) -> "Dataset":
        """Execute the plan; the result holds materialized block refs
        (reference: dataset.py materialize:4622).  Per-operator timings
        are collected opportunistically during this run (the timed
        executor's second return) and stashed on both this dataset and
        the result, so a following ``stats()`` reports THIS execution
        instead of profiling a second one."""
        sink: List = []
        refs, counts = self._materialize_refs(timed_sink=sink)
        stats = None
        if sink:
            try:
                stats = _aggregate_op_rows(ray_tpu.get(sink))
            except Exception:
                stats = None  # timing is best-effort, never fails the run
        out = Dataset([(r, []) for r in refs], counts,
                      logical=self._logical)
        self._materialized_stats = stats
        out._materialized_stats = stats
        return out

    # --------------------------------------------------------- plan insight

    def explain(self) -> str:
        """The logical plan, its optimized form, and the rules that fired
        (reference: logical/optimizers.py — LogicalOptimizer rule list)."""
        optimized, fired = self._logical.optimize()
        lines = ["-- logical plan --", self._logical.describe(),
                 "-- optimized (physical stages) --", optimized.describe()]
        if fired:
            lines += ["-- rules fired --"] + [f"  {r}" for r in fired]
        lines.append(f"-- execution: {len(self._parts)} block(s), "
                     "fused chain = one task per block --")
        return "\n".join(lines)

    def stats(self) -> Dict[str, Any]:
        """Per-operator rows/wall breakdown plus the optimized stage list
        (reference: dataset.py stats:4790 returns per-operator
        wall/rows/output sizes).

        If this dataset ran (or came out of) ``materialize()``, the
        breakdown is that run's opportunistically collected timings —
        no extra work.  OTHERWISE THIS METHOD EXECUTES THE WHOLE PLAN
        once more in a profiled pass: side-effecting UDFs run AGAIN and
        large reads decode AGAIN.  Call ``materialize()`` first when that
        matters.  (Pool-routed chains also materialize through their
        actor pool before profiling, so their breakdown collapses to the
        materialized source.)"""
        operators = self._materialized_stats
        source = "last_materialize"
        if operators is None:
            source = "profiled_pass"
            timing_refs = [
                _exec_part_timed.options(num_returns=2).remote(src, ops)[1]
                for src, ops in self._plan_parts()
            ]
            operators = _aggregate_op_rows(ray_tpu.get(timing_refs))
        optimized, fired = self._logical.optimize()
        return {
            "operators": operators,
            "operators_source": source,
            "num_blocks": len(self._parts),
            # Map chains execute inside ONE task per block — the physical
            # realization of the fusion rule.
            "tasks_per_block": 1,
            "optimized_stages": [op.describe() for op in optimized.ops],
            "rules_fired": fired,
            "last_execution": dict(
                DataContext.get_current().last_execution_stats),
        }

    # ---------------------------------------------------------- consumption

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_blocks: int = 2,
        device: Any = None,
    ) -> Iterator[Any]:
        """Stream batches (reference: dataset.py iter_batches:3675).  With
        ``device=`` each batch is jax.device_put ahead of consumption
        (double buffering — the iter_torch_batches analog for TPU)."""
        from .iterator import batches_from_blocks, device_prefetch

        def blocks() -> Iterator[Block]:
            refs: deque = deque()
            it = self._iter_block_refs()
            for ref in it:
                refs.append(ref)
                if len(refs) > prefetch_blocks:
                    yield ray_tpu.get(refs.popleft())
            while refs:
                yield ray_tpu.get(refs.popleft())

        batch_size = batch_size or DataContext.get_current().default_batch_size
        out = batches_from_blocks(
            blocks(), batch_size, batch_format, drop_last
        )
        if device is not None:
            out = device_prefetch(out, device)
        return out

    def iter_torch_batches(self, *, batch_size: Optional[int] = None,
                           drop_last: bool = False) -> Iterator[Dict]:
        """CPU-torch batches (reference: dataset.py iter_torch_batches:3746)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            yield {k: torch.from_numpy(np.ascontiguousarray(v))
                   for k, v in batch.items()}

    def iter_rows(self) -> Iterator[Dict]:
        for block in self.iter_blocks():
            yield from block.rows()

    def take(self, k: int = 20) -> List[Dict]:
        out: List[Dict] = []
        for block in self.iter_blocks():
            for row in block.rows():
                out.append(row)
                if len(out) >= k:
                    return out
        return out

    def take_all(self) -> List[Dict]:
        out: List[Dict] = []
        for block in self.iter_blocks():
            out.extend(block.rows())
        return out

    def count(self) -> int:
        if self._counts is not None:
            return sum(self._counts)
        if self._total_rows is not None:
            return self._total_rows
        return sum(ray_tpu.get(
            [_part_rows.remote(src, ops)
             for src, ops in self._plan_parts()]
        ))

    def schema(self) -> Dict[str, str]:
        for block in self.iter_blocks():
            if block.num_rows:
                return block.schema()
        return {}

    def columns(self) -> List[str]:
        return list(self.schema())

    def _agg(self, col: str, kind: str):
        partials = [p for p in ray_tpu.get(
            [_part_agg.remote(src, ops, col, kind)
             for src, ops in self._plan_parts()]
        ) if p is not None]
        if not partials:
            return None
        vals = [v for v, _ in partials]
        if kind == "sum":
            return sum(vals)
        return min(vals) if kind == "min" else max(vals)

    def show(self, limit: int = 20) -> None:
        """Print the first ``limit`` rows (reference: dataset.py show)."""
        for row in self.take(limit):
            print(row)

    def to_pandas(self, limit: Optional[int] = None):
        """Materialize into one pandas DataFrame (reference: dataset.py
        to_pandas — caps at a row limit to protect the driver)."""
        import pandas as pd

        ds = self.limit(limit) if limit is not None else self
        frames = [
            pd.DataFrame(block.to_numpy())
            for block in ds.iter_blocks() if block.num_rows
        ]
        if not frames:
            return pd.DataFrame()
        return pd.concat(frames, ignore_index=True)

    def std(self, col: str, ddof: int = 1):
        """Column standard deviation via per-part (sum, sumsq, n) partials
        (reference: dataset.py std — the same Welford-free formulation)."""
        partials = [p for p in ray_tpu.get(
            [_part_agg.remote(src, ops, col, "sumsq")
             for src, ops in self._plan_parts()]
        ) if p is not None]
        if not partials:
            return None
        s = sum(v for (v, _), _ in partials)
        ss = sum(v for (_, v), _ in partials)
        n = sum(c for _, c in partials)
        if n <= ddof:
            return None
        var = (ss - s * s / n) / (n - ddof)
        return float(np.sqrt(max(var, 0.0)))

    def sum(self, col: str):
        return self._agg(col, "sum")

    def min(self, col: str):
        return self._agg(col, "min")

    def max(self, col: str):
        return self._agg(col, "max")

    def mean(self, col: str):
        partials = [p for p in ray_tpu.get(
            [_part_agg.remote(src, ops, col, "sum")
             for src, ops in self._plan_parts()]
        ) if p is not None]
        total = sum(v for v, _ in partials)
        n = sum(c for _, c in partials)
        return total / n if n else None

    def groupby(self, key: str) -> "GroupedDataset":
        """Group rows by a key column (reference: dataset.py groupby:1822 ->
        grouped_data.py aggregations).  Per-block partial aggregates run as
        tasks; the driver combines per key."""
        return GroupedDataset(self, key)

    # ------------------------------------------------------------- splitting

    def split(self, n: int) -> List["Dataset"]:
        """Materialize and split into n disjoint datasets, blocks assigned
        round-robin (reference: dataset.py split:1337)."""
        refs, counts = self._materialize_refs()
        out = []
        for i in builtins.range(n):
            mine = [(refs[j], []) for j in builtins.range(i, len(refs), n)]
            mine_counts = [counts[j] for j in builtins.range(i, len(refs), n)]
            out.append(Dataset(mine, mine_counts))
        return out

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        """n coordinated iterators over disjoint streams of this dataset —
        the Train ingest path (reference: output_splitter.py OutputSplitter,
        dataset.py streaming_split).  `equal`/`locality_hints` accepted for
        API parity; blocks are handed out round-robin on demand."""
        from .split import make_split_iterators

        return make_split_iterators(self, n)

    # ---------------------------------------------------------------- output

    def write_parquet(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        ray_tpu.get([
            _write_parquet_task.remote(
                src, ops, os.path.join(path, f"part-{i:05d}.parquet")
            )
            for i, (src, ops) in enumerate(self._plan_parts())
        ])

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        ray_tpu.get([
            _write_csv_task.remote(
                src, ops, os.path.join(path, f"part-{i:05d}.csv")
            )
            for i, (src, ops) in enumerate(self._plan_parts())
        ])

    def write_json(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        ray_tpu.get([
            _write_json_task.remote(
                src, ops, os.path.join(path, f"part-{i:05d}.json")
            )
            for i, (src, ops) in enumerate(self._plan_parts())
        ])

    def num_blocks(self) -> int:
        return len(self._parts)

    def __repr__(self) -> str:
        return (
            f"Dataset(num_blocks={len(self._parts)}, "
            f"count={sum(self._counts) if self._counts is not None else '?'})"
        )


# ------------------------------------------------------------------ sources


def from_items(items: Sequence[Any], *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    n = override_num_blocks or min(
        DataContext.get_current().default_num_blocks, max(len(items), 1)
    )
    parts = []
    counts = []
    for i in builtins.range(n):
        chunk = items[len(items) * i // n: len(items) * (i + 1) // n]
        if not chunk:
            continue
        parts.append((functools.partial(Block.from_items, list(chunk)), []))
        counts.append(len(chunk))
    return Dataset(parts, counts)


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:
    nb = override_num_blocks or DataContext.get_current().default_num_blocks

    def make(lo: int, hi: int) -> Block:
        return Block({"id": np.arange(lo, hi, dtype=np.int64)})

    parts = []
    counts = []
    for i in builtins.range(nb):
        lo, hi = n * i // nb, n * (i + 1) // nb
        if lo < hi:
            parts.append((functools.partial(make, lo, hi), []))
            counts.append(hi - lo)
    return Dataset(parts, counts)


def range_tensor(n: int, *, shape: Sequence[int] = (1,),
                 override_num_blocks: Optional[int] = None) -> Dataset:
    nb = override_num_blocks or DataContext.get_current().default_num_blocks
    shape = tuple(shape)

    def make(lo: int, hi: int) -> Block:
        ids = np.arange(lo, hi, dtype=np.int64)
        data = np.broadcast_to(
            ids.reshape((-1,) + (1,) * len(shape)), (hi - lo,) + shape
        ).copy()
        return Block({"data": data})

    parts = []
    counts = []
    for i in builtins.range(nb):
        lo, hi = n * i // nb, n * (i + 1) // nb
        if lo < hi:
            parts.append((functools.partial(make, lo, hi), []))
            counts.append(hi - lo)
    return Dataset(parts, counts)


def from_numpy(arr: np.ndarray, column: str = "data", *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    nb = override_num_blocks or DataContext.get_current().default_num_blocks
    parts = []
    counts = []
    for i in builtins.range(nb):
        lo, hi = len(arr) * i // nb, len(arr) * (i + 1) // nb
        if lo < hi:
            chunk = arr[lo:hi].copy()
            parts.append((functools.partial(Block.from_batch, {column: chunk}), []))
            counts.append(hi - lo)
    return Dataset(parts, counts)


def from_arrow(table) -> Dataset:
    return Dataset([(functools.partial(Block.from_arrow, table), [])],
                   [table.num_rows])


def from_pandas(df) -> Dataset:
    return Dataset(
        [(functools.partial(
            Block.from_batch, {c: df[c].to_numpy() for c in df.columns}), [])],
        [len(df)],
    )


def _expand_paths(paths: Union[str, Sequence[str]], suffixes) -> List[str]:
    import os

    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p))
                if f.endswith(suffixes)
            )
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no input files under {paths}")
    return out


def _read_image_file(f: str, *, size: Optional[tuple] = None,
                     mode: str = "RGB",
                     include_paths: bool = False) -> Block:
    from PIL import Image

    with Image.open(f) as im:
        im = im.convert(mode)
        if size is not None:
            im = im.resize((size[1], size[0]))  # PIL takes (w, h)
        arr = np.asarray(im, dtype=np.uint8)
    cols = {"image": arr[None]}
    if include_paths:
        cols["path"] = np.array([f], dtype=object)
    return Block.from_batch(cols)


def _read_source(kind: str, files: List[str],
                 override_num_blocks: Optional[int],
                 columns: Optional[List[str]] = None,
                 reader_kwargs=None) -> Dataset:
    """One read task per file (reference: read_api.py splits files across
    read tasks; per-file granularity is the common case).  Sources are
    _ReadTask objects so projection/limit pushdown can rewrite them."""
    n = override_num_blocks or len(files)
    n = min(n, len(files))
    parts = []
    for i in builtins.range(n):
        chunk = files[len(files) * i // n: len(files) * (i + 1) // n]
        if chunk:
            parts.append((_ReadTask(kind, chunk, columns,
                                    reader_kwargs=reader_kwargs), []))
    lop = LogicalOp("read", f"Read{kind.capitalize()}", {
        "files": len(files), "columns": columns,
        "supports_columns": kind in _ReadTask.SUPPORTS_COLUMNS,
        "supports_limit": True,
    })
    return Dataset(parts, logical=LogicalPlan([lop]))


def read_parquet(paths, *, override_num_blocks: Optional[int] = None,
                 columns: Optional[List[str]] = None) -> Dataset:
    return _read_source(
        "parquet", _expand_paths(paths, (".parquet",)),
        override_num_blocks, columns,
    )


def read_csv(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read_source(
        "csv", _expand_paths(paths, (".csv",)), override_num_blocks
    )


def read_json(paths, *, override_num_blocks: Optional[int] = None) -> Dataset:
    return _read_source(
        "json", _expand_paths(paths, (".json", ".jsonl")),
        override_num_blocks
    )


def read_images(paths, *, size: Optional[tuple] = None, mode: str = "RGB",
                include_paths: bool = False,
                override_num_blocks: Optional[int] = None) -> Dataset:
    """Image files -> blocks with an "image" uint8 tensor column
    (reference: data/read_api.py read_images / datasource ImageDatasource).
    ``size=(h, w)`` resizes so the column has a uniform tensor shape —
    required when source images vary (the batch format is dense numpy)."""
    return _read_source(
        "images",
        _expand_paths(paths, (".png", ".jpg", ".jpeg", ".bmp", ".gif")),
        override_num_blocks,
        reader_kwargs={"size": size, "mode": mode,
                       "include_paths": include_paths},
    )
