"""ray_tpu.data — streaming dataset library (Ray Data equivalent).

Role-equivalent to the reference's Ray Data (reference:
python/ray/data/dataset.py:139 Dataset API;
data/_internal/execution/streaming_executor.py:48 pull-based streaming
execution; data/_internal/execution/operators/output_splitter.py
streaming_split feeding Train workers).  TPU-first design choices:

- Blocks are Arrow tables at rest and dict-of-numpy batches in flight — the
  batch format `jax.device_put` consumes directly (reference keeps Arrow /
  pandas blocks and converts per-batch, data/block.py:221 BlockAccessor).
- Execution is a bounded-window pull pipeline of remote tasks over the
  cluster; `iter_batches` double-buffers `jax.device_put` so the TPU never
  waits on host→HBM transfer (the "Arrow→TPU pipeline" north star).
- `streaming_split(n)` hands blocks to n consumers through a coordinator
  actor (the OutputSplitter analog) so Train workers across nodes each pull
  a disjoint stream.
"""

from .block import Block
from .context import DataContext
from .dataset import (
    ActorPoolStrategy,
    Dataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A001  (shadows builtins.range on purpose, like the reference)
    range_tensor,
    read_csv,
    read_images,
    read_json,
    read_parquet,
)
from .iterator import DataIterator

__all__ = [
    "ActorPoolStrategy", "Block", "DataContext", "Dataset", "DataIterator",
    "from_arrow", "from_items", "from_numpy", "from_pandas",
    "range", "range_tensor", "read_csv", "read_images", "read_json",
    "read_parquet",
]
