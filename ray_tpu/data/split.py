"""streaming_split coordinator: one actor hands disjoint block streams to n
consumers.

Role-equivalent to the reference's OutputSplitter operator (reference:
data/_internal/execution/operators/output_splitter.py — round-robin block
routing to n output splits, driven by the streaming executor;
dataset.py streaming_split returns per-split DataIterators).  The
coordinator executes the plan once (first epoch) while assigning block refs
round-robin; later epochs replay the cached assignment, so every Train
worker sees the same shard every epoch.
"""

from __future__ import annotations

from typing import Any, List, Optional

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class _SplitCoordinator:
    """Owns plan execution and the per-split block assignment.  Actor method
    calls are serialized (per-actor FIFO), so no locking is needed."""

    def __init__(self, parts: List[tuple], n: int):
        from .dataset import Dataset

        self._ds = Dataset(parts)
        self._n = n
        self._assignment: List[List[Any]] = [[] for _ in range(n)]
        self._iter = None
        self._exhausted = False
        self._epochs = [0] * n

    def begin_epoch(self, split: int) -> int:
        self._epochs[split] += 1
        return self._epochs[split]

    def _pull_until(self, split: int, pos: int) -> None:
        """Drive the streaming executor until `split` has > pos blocks
        assigned (or the plan is exhausted)."""
        if self._iter is None and not self._exhausted:
            self._iter = self._ds._iter_block_refs()
        while not self._exhausted and len(self._assignment[split]) <= pos:
            try:
                ref = next(self._iter)
            except StopIteration:
                self._exhausted = True
                self._iter = None
                return
            # Assign to the currently shortest queue: balanced splits even
            # when consumers advance at different paces.
            target = min(range(self._n), key=lambda i: len(self._assignment[i]))
            self._assignment[target].append(ref)

    def next_block(self, split: int, epoch: int, pos: int) -> Optional[Any]:
        """The pos-th block ref of `split`, or None when the split's stream
        is exhausted for this epoch."""
        self._pull_until(split, pos)
        q = self._assignment[split]
        if pos < len(q):
            return q[pos]
        return None

    def stats(self) -> dict:
        return {
            "splits": self._n,
            "blocks_per_split": [len(q) for q in self._assignment],
            "exhausted": self._exhausted,
            "epochs": list(self._epochs),
        }


def make_split_iterators(ds, n: int) -> List["DataIterator"]:
    from .iterator import DataIterator

    coord = _SplitCoordinator.remote(ds._parts, n)
    return [DataIterator(coord, i) for i in range(n)]
