"""Logical plan + optimizer for Dataset pipelines.

Role-equivalent to the reference's logical/physical plan stack (reference:
python/ray/data/_internal/logical/interfaces/logical_plan.py,
logical/optimizers.py:36-54 LogicalOptimizer/PhysicalOptimizer rule lists —
notably OperatorFusionRule in physical_optimizer.py and column/limit
pushdown in logical/rules/).  The repo's physical executor runs one task
per block over a (source, [op, ...]) chain, so MAP FUSION is realized by
keeping fused ops in one chain (one task per block — exactly what the
reference's fusion rule produces), and READ PUSHDOWN rewrites the read
source itself (column-pruned / row-limited file reads).

The logical plan is the authoritative, inspectable description: every
Dataset transform appends a LogicalOp; optimize() applies the rule list
and records what fired; Dataset.explain() prints both plans.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

# Op kinds that are per-block row transforms — safely fusable into one task
# (reference: physical_optimizer.py OperatorFusionRule fuses Map->Map).
_FUSABLE = {"map_batches", "map", "flat_map", "filter", "project",
            "add_column", "drop_column"}


@dataclasses.dataclass
class LogicalOp:
    """One node of the (linear) logical plan."""

    kind: str                    # "read" | "map_batches" | "project" | ...
    name: str                    # display name, e.g. MapBatches(normalize)
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def describe(self) -> str:
        extra = ""
        if self.payload:
            inner = ", ".join(f"{k}={v!r}" for k, v in self.payload.items()
                              if v is not None)
            if inner:
                extra = f" [{inner}]"
        return f"{self.name}{extra}"


class LogicalPlan:
    def __init__(self, ops: Optional[List[LogicalOp]] = None):
        self.ops: List[LogicalOp] = list(ops or [])

    def appended(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])

    def optimize(self) -> Tuple["LogicalPlan", List[str]]:
        """Apply the rule list; returns (optimized plan, rules that fired).

        Rules (reference: logical/optimizers.py:36-54):
        - ReadPushdown: Project/Limit immediately after a pushdown-capable
          Read folds into the read op (column-pruned / row-limited files).
        - FuseMaps: adjacent per-block row transforms collapse into one
          FusedMap stage == one task per block at execution time.
        """
        ops = list(self.ops)
        fired: List[str] = []

        # -- read pushdown ---------------------------------------------------
        changed = True
        while changed:
            changed = False
            if len(ops) >= 2 and ops[0].kind == "read":
                read = ops[0]
                nxt = ops[1]
                if (nxt.kind == "project"
                        and read.payload.get("supports_columns")
                        and not read.payload.get("columns")):
                    merged = dataclasses.replace(
                        read, payload={**read.payload,
                                       "columns": nxt.payload["columns"]})
                    ops = [merged] + ops[2:]
                    fired.append(
                        f"ReadPushdown: {nxt.describe()} -> {read.name}")
                    changed = True
                elif nxt.kind == "limit" and read.payload.get(
                        "supports_limit"):
                    merged = dataclasses.replace(
                        read, payload={**read.payload,
                                       "limit": nxt.payload["n"]})
                    ops = [merged] + ops[2:]
                    fired.append(
                        f"ReadPushdown: {nxt.describe()} -> {read.name}")
                    changed = True

        # -- map fusion ------------------------------------------------------
        fused: List[LogicalOp] = []
        for op in ops:
            if (op.kind in _FUSABLE and fused
                    and fused[-1].kind in ("fused_map", *_FUSABLE)):
                prev = fused.pop()
                members = prev.payload.get("members", [prev.name])
                members = members + [op.name]
                fused.append(LogicalOp(
                    "fused_map", f"FusedMap[{' -> '.join(members)}]",
                    {"members": members, "tasks_per_block": 1}))
                if len(members) == 2:
                    fired.append(
                        f"FuseMaps: {members[0]} + {members[1]}")
                else:
                    fired[-1] = ("FuseMaps: " + " + ".join(members))
            else:
                fused.append(op)
        return LogicalPlan(fused), fired

    def describe(self) -> str:
        if not self.ops:
            return "(empty plan)"
        return "\n".join(
            ("  " * i) + ("-> " if i else "") + op.describe()
            for i, op in enumerate(self.ops)
        )
