"""Per-process dataset execution configuration.

Role-equivalent to the reference's DataContext (reference:
python/ray/data/context.py) — a process-wide singleton consulted at plan
execution time, deliberately small: the streaming executor here has two
tunables (task window, default block count) instead of the reference's
several dozen.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class DataContext:
    # Max dataset tasks in flight per execution (pull-based backpressure —
    # reference: streaming_executor_state.py select_operator_to_run caps
    # concurrent tasks by resource budget).
    execution_window: int = 16
    # Default number of blocks for sources that don't specify parallelism
    # (reference: DataContext.min_parallelism / target block sizing).
    default_num_blocks: int = 8
    # Rows per batch when iter_batches is not given a batch_size.
    default_batch_size: int = 256
    # Byte budget for in-flight blocks: the executor shrinks its task
    # window so (in-flight blocks x learned mean block size) stays under
    # this bound (reference: execution/backpressure_policy/ +
    # resource_manager.py budgets).  None disables byte-based backpressure.
    max_in_flight_bytes: "int | None" = 256 * 1024 * 1024
    # The byte budget never shrinks the window below this floor (keeps the
    # pipeline from collapsing to serial on one huge block).
    min_execution_window: int = 2
    # Stats of the most recent plan execution in this process:
    # {"peak_in_flight": int, "submitted": int, "effective_window_min": int}.
    last_execution_stats: dict = dataclasses.field(default_factory=dict)

    _current = None

    @staticmethod
    def get_current() -> "DataContext":
        if DataContext._current is None:
            DataContext._current = DataContext()
        return DataContext._current
