"""Data blocks: Arrow tables at rest, dict-of-numpy batches in flight.

Role-equivalent to the reference's Block/BlockAccessor (reference:
python/ray/data/block.py:61 BlockType, :196 BlockMetadata, :221
BlockAccessor; arrow_block.py, pandas_block.py).  Two physical layouts:

- ``pyarrow.Table`` — tabular data read from files; zero-copy slicing.
- ``dict[str, np.ndarray]`` — tensor data (any column may be n-dimensional),
  the layout ``jax.device_put`` consumes directly.  Arrow cannot hold
  multi-dim columns without extension types, so tensor blocks stay numpy.

All transforms normalize through ``to_numpy()``; conversions between the
two layouts are explicit and lossless for 1-D numeric data.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

try:
    import pyarrow as pa
except ImportError:  # pragma: no cover - pyarrow is in the image
    pa = None

Batch = Dict[str, np.ndarray]


def _normalize_column(values: Any) -> np.ndarray:
    arr = np.asarray(values)
    if arr.dtype == object and arr.ndim == 0:
        arr = arr.reshape(1)
    return arr


class Block:
    """One immutable chunk of a dataset."""

    __slots__ = ("_data",)

    def __init__(self, data: Union["pa.Table", Batch]):
        self._data = data

    # -- constructors ---------------------------------------------------------

    @staticmethod
    def from_items(items: Sequence[Any]) -> "Block":
        """Rows from a python list.  Dicts become columns; scalars become a
        single ``item`` column (reference: from_items wraps non-dict rows in
        an 'item' column)."""
        if items and isinstance(items[0], dict):
            cols: Dict[str, List[Any]] = {}
            for row in items:
                for k, v in row.items():
                    cols.setdefault(k, []).append(v)
            return Block({k: _normalize_column(v) for k, v in cols.items()})
        return Block({"item": _normalize_column(list(items))})

    @staticmethod
    def from_batch(batch: Batch) -> "Block":
        out: Batch = {}
        n = None
        for k, v in batch.items():
            arr = _normalize_column(v)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"batch columns disagree on length: {k} has {len(arr)}, "
                    f"expected {n}"
                )
            out[k] = arr
        return Block(out)

    @staticmethod
    def from_arrow(table: "pa.Table") -> "Block":
        return Block(table)

    # -- introspection --------------------------------------------------------

    @property
    def is_arrow(self) -> bool:
        return pa is not None and isinstance(self._data, pa.Table)

    @property
    def num_rows(self) -> int:
        if self.is_arrow:
            return self._data.num_rows
        if not self._data:
            return 0
        return len(next(iter(self._data.values())))

    @property
    def size_bytes(self) -> int:
        if self.is_arrow:
            return self._data.nbytes
        return sum(a.nbytes for a in self._data.values())

    def columns(self) -> List[str]:
        if self.is_arrow:
            return self._data.column_names
        return list(self._data)

    def schema(self) -> Dict[str, str]:
        if self.is_arrow:
            return {f.name: str(f.type) for f in self._data.schema}
        return {
            k: f"{a.dtype}{list(a.shape[1:]) if a.ndim > 1 else ''}"
            for k, a in self._data.items()
        }

    # -- layout conversions ---------------------------------------------------

    def to_numpy(self) -> Batch:
        if not self.is_arrow:
            return dict(self._data)
        out: Batch = {}
        for name in self._data.column_names:
            col = self._data.column(name)
            if col.num_chunks > 1:
                col = col.combine_chunks()
            elif col.num_chunks == 1:
                col = col.chunk(0)
            try:
                out[name] = col.to_numpy(zero_copy_only=False)
            except (pa.ArrowInvalid, NotImplementedError):
                out[name] = np.asarray(col.to_pylist(), dtype=object)
        return out

    def to_arrow(self) -> "pa.Table":
        if self.is_arrow:
            return self._data
        for k, a in self._data.items():
            if a.ndim > 1:
                raise ValueError(
                    f"column {k!r} is {a.ndim}-D; Arrow tables hold 1-D "
                    "columns only — keep tensor data in numpy blocks"
                )
        return pa.table({k: pa.array(a) for k, a in self._data.items()})

    def to_pandas(self):
        import pandas as pd

        if self.is_arrow:
            return self._data.to_pandas()
        return pd.DataFrame(
            {k: list(v) if v.ndim > 1 else v for k, v in self._data.items()}
        )

    # -- row/slice access -----------------------------------------------------

    def slice(self, start: int, end: int) -> "Block":
        """Zero-copy row range [start, end)."""
        if self.is_arrow:
            return Block(self._data.slice(start, end - start))
        return Block({k: a[start:end] for k, a in self._data.items()})

    def take_rows(self, indices: np.ndarray) -> "Block":
        if self.is_arrow:
            return Block(self._data.take(pa.array(indices)))
        return Block({k: a[indices] for k, a in self._data.items()})

    def select(self, columns: Sequence[str]) -> "Block":
        if self.is_arrow:
            return Block(self._data.select(list(columns)))
        return Block({k: self._data[k] for k in columns})

    def rows(self) -> Iterator[Dict[str, Any]]:
        batch = self.to_numpy()
        keys = list(batch)
        for i in range(self.num_rows):
            yield {k: batch[k][i] for k in keys}

    # -- combination ----------------------------------------------------------

    @staticmethod
    def concat(blocks: Sequence["Block"]) -> "Block":
        blocks = [b for b in blocks if b.num_rows > 0]
        if not blocks:
            return Block({})
        if all(b.is_arrow for b in blocks):
            return Block(pa.concat_tables([b._data for b in blocks]))
        batches = [b.to_numpy() for b in blocks]
        keys = list(batches[0])
        return Block(
            {k: np.concatenate([bt[k] for bt in batches]) for k in keys}
        )

    def __repr__(self) -> str:
        return (
            f"Block(rows={self.num_rows}, "
            f"layout={'arrow' if self.is_arrow else 'numpy'}, "
            f"cols={self.columns()})"
        )
