"""Worker-side train session: report(), get_checkpoint(), rank info, dataset
shards.

Role-equivalent to the reference's per-worker _TrainSession
(reference: train/_internal/session.py — report:403, public report:667,
get_checkpoint:754) with the same synchronous-collective semantics: report()
blocks until the driver has consumed the round, keeping workers in lockstep
(which is exactly what an SPMD TPU job wants).
"""

from __future__ import annotations

import os
import queue
import shutil
import tempfile
import threading
from typing import Any, Dict, Iterator, Optional

from .checkpoint import Checkpoint

_session: Optional["TrainSession"] = None

_m_data_wait = None


def _observe_data_wait(seconds: float) -> None:
    """Rank-side data-wait histogram — lazily resolved so sessions built
    directly in unit tests don't spin up the metrics registry."""
    global _m_data_wait
    try:
        from ..util.metrics import get_histogram

        if _m_data_wait is None:
            _m_data_wait = get_histogram(
                "ray_tpu_gang_data_wait_seconds",
                "Per-round dataset wait observed by one gang rank")
        _m_data_wait.observe(seconds)
    except Exception:
        pass  # metrics must never fail a training round


class _TimedShard:
    """Transparent dataset-shard proxy: times blocking iteration (and any
    ``iter_batches`` stream) so report() can attribute the round's data
    wait.  Everything else delegates to the wrapped shard."""

    def __init__(self, shard, session: "TrainSession"):
        self._shard = shard
        self._session = session

    def __getattr__(self, name):
        return getattr(self._shard, name)

    def __iter__(self):
        return self._timed(iter(self._shard))

    def iter_batches(self, *args, **kwargs):
        return self._timed(self._shard.iter_batches(*args, **kwargs))

    def _timed(self, it: Iterator):
        import time as _time

        from ..util import chaos as _chaos

        s = self._session
        while True:
            t0 = _time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                s._data_wait_s += _time.perf_counter() - t0
                return
            # Chaos straggler injection ("data"): inside the timed window,
            # so the injected delay is attributed as data wait.
            _chaos.maybe_straggle("data", s.world_rank)
            s._data_wait_s += _time.perf_counter() - t0
            yield item


class TrainSession:
    def __init__(
        self,
        world_rank: int,
        world_size: int,
        trial_dir: str,
        restored_checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[Dict[str, Any]] = None,
    ):
        self.world_rank = world_rank
        self.world_size = world_size
        self.trial_dir = trial_dir
        self.restored_checkpoint = restored_checkpoint
        self.dataset_shards = dataset_shards or {}
        # Set at worker setup when ScalingConfig.mesh is given: the jax Mesh
        # every rank shards its train step over (ray_tpu.train.get_mesh()).
        self.mesh = None
        # Name of the gang's host-side collective group (cross-worker
        # allreduce of metrics/grads outside compiled programs).
        self.collective_group: Optional[str] = None
        self.result_queue: "queue.Queue" = queue.Queue()
        self.consumed = threading.Semaphore(0)
        self.step = 0
        self.finished = False
        # Preemption drain: the DRIVER observes node drains and piggybacks
        # the signal on the lockstep ack (see ack(should_checkpoint=True)),
        # so every rank's should_checkpoint() flips at the same round
        # boundary — a gang-wide same-step drain save (per-rank pubsub
        # delivery would skew ranks by a round and persist partial-rank
        # checkpoints).  Reporting a checkpoint clears the flag.
        self._drain_pending = False
        # Peer-replicated in-memory checkpoints: ring successor's actor
        # handle + cadence (set by WorkerGroup after all ranks are placed),
        # and this process's view of replicated snapshots —
        # {rank: [(step, ObjectRef-of-packed-dir), ...]} holding its OWN
        # latest snapshots plus any peer snapshots pushed to it.  The last
        # TWO per rank are kept: lockstep reporting bounds rank skew to one
        # round, so two generations guarantee a common step exists across
        # the gang even when a node dies mid-round.
        self._peer_handle = None
        self._memory_every_k: Optional[int] = None
        self._ckpt_count = 0
        # Guarded by _snapshots_lock: pushed to by the peer's RPC thread
        # while the train loop replicates and the driver collects.
        self.memory_snapshots: Dict[int, list] = {}
        self._snapshots_lock = threading.Lock()
        # Goodput accounting (train/telemetry.py): report() derives step
        # time / tokens-per-sec / MFU per round and both sets the
        # ray_tpu_train_* gauges and merges the numbers into the reported
        # metrics.  Created lazily so sessions built directly in unit
        # tests don't spin up the metrics flusher.
        self._telemetry = None
        self._last_report_t: Optional[float] = None
        # Gang round flight recorder (util/gangrec.py): every report()
        # appends ONE fixed-size record attributing the round across
        # data / compute / collective / checkpoint / lockstep-ack, joined
        # head-side by (gang, round) into skew profiles.  gang_id is set
        # by WorkerGroup.setup (one id per gang incarnation); the phase
        # accumulators are touched only by the train loop thread —
        # report() is synchronous — so they need no lock.
        self.gang_id: Optional[str] = None
        self._data_wait_s = 0.0
        self._coll_base: Optional[Dict[str, Any]] = None
        self._compile_base = 0.0

    @property
    def telemetry(self):
        if self._telemetry is None:
            from .telemetry import TrainTelemetry

            self._telemetry = TrainTelemetry(rank=self.world_rank)
        return self._telemetry

    # ---- drain / in-memory replication wiring -------------------------------

    def should_checkpoint(self) -> bool:
        """True when a preemption drain was announced and no checkpoint has
        been reported since — the user loop should save NOW, ahead of its
        periodic cadence (reference shape: TorchTitan/elastic trainers
        checkpoint on SIGTERM notice)."""
        return self._drain_pending

    def configure_memory_checkpoints(self, peer_handle,
                                     every_k: Optional[int]) -> None:
        self._peer_handle = peer_handle
        self._memory_every_k = every_k

    def remember_snapshot(self, rank: int, step: int, ref) -> None:
        """Record an in-memory snapshot handle for ``rank``, keeping the
        last two generations (older refs drop → their store segments free)."""
        with self._snapshots_lock:
            entries = self.memory_snapshots.setdefault(rank, [])
            entries.append((step, ref))
            del entries[:-2]

    def snapshot_view(self) -> Dict[int, list]:
        """Consistent copy of the replica table (safe against concurrent
        peer pushes)."""
        with self._snapshots_lock:
            return {r: list(v) for r, v in self.memory_snapshots.items()}

    def _replicate_checkpoint(self, staged_dir: str) -> None:
        """Push this rank's host snapshot into the object store (own node)
        and to its ring peer's store, so a new gang can restore from memory
        after this rank's node dies.  Best-effort: replication must never
        fail a training round."""
        import ray_tpu

        from .checkpoint import pack_directory

        blob = pack_directory(staged_dir)
        # Own copy: survives THIS PROCESS dying (worker crash) as long as
        # the node's store daemon lives; the driver re-owns it at collection.
        self.remember_snapshot(self.world_rank, self.step, ray_tpu.put(blob))
        if self._peer_handle is not None:
            # Peer copy: survives this NODE dying.  CONFIRMED, not fire-and-
            # forget: the trainer skips the disk write on the strength of
            # this replica, so an unacknowledged push must surface here
            # (the caller then reports memory_replicated=False and the
            # round persists to disk instead).  No await cycle: the peer's
            # handler only does a local put on its own concurrency slot.
            ray_tpu.get(
                self._peer_handle.store_peer_snapshot.remote(
                    self.world_rank, self.step, blob
                ),
                timeout=30.0,
            )

    # ---- called from user train loop ----------------------------------------

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        import time as _time

        from ..util import chaos as _chaos

        # Chaos straggler injection ("compute"): a slowdown here is
        # indistinguishable from a slow step body — it lands in the round
        # record's compute residual.
        _chaos.maybe_straggle("compute", self.world_rank)
        self.step += 1
        metrics = self._augment_metrics(dict(metrics))
        persisted = None
        replicated = False
        ckpt_s = 0.0
        if checkpoint is not None:
            ckpt_t0 = _time.perf_counter()
            _chaos.maybe_straggle("checkpoint", self.world_rank)
            # Stage the worker's checkpoint under the trial dir so it outlives
            # the user's temp directory.
            dest = os.path.join(
                self.trial_dir, "staging",
                f"step_{self.step:06d}_rank_{self.world_rank}",
            )
            shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            persisted = dest
            self._ckpt_count += 1
            drain_save = self._drain_pending
            self._drain_pending = False
            if self._memory_every_k is not None and (
                    drain_save
                    or (self._ckpt_count % self._memory_every_k) == 0):
                try:
                    self._replicate_checkpoint(dest)
                    replicated = True
                except Exception:
                    pass  # replication is best-effort by design
            ckpt_s = _time.perf_counter() - ckpt_t0
        else:
            drain_save = False
        self.result_queue.put(
            {"metrics": metrics, "checkpoint_dir": persisted,
             "step": self.step, "rank": self.world_rank,
             "drain": drain_save, "memory_replicated": replicated}
        )
        # Lockstep with the driver (reference behavior: session.report blocks
        # until the round is processed).
        ack_t0 = _time.perf_counter()
        self.consumed.acquire()
        now = _time.perf_counter()
        self._emit_round(metrics, ckpt_s=ckpt_s, ack_s=now - ack_t0)
        # Step time measures the user's loop body, not the driver's round
        # processing: restart the clock after the lockstep wait returns.
        self._last_report_t = _time.perf_counter()

    def _emit_round(self, metrics: Dict[str, Any], *, ckpt_s: float,
                    ack_s: float) -> None:
        """Append this round's flight record (util/gangrec.py).  All the
        goodput numbers come from the SAME telemetry.record_step sample
        that _augment_metrics merged into the reported metrics — the round
        record and the metrics history can never disagree.  Best-effort:
        recording must never fail a training round."""
        try:
            import time as _time

            from ..collective import collective as _coll
            from ..util import gangrec

            tel = self.telemetry.last
            totals = _coll.op_totals()
            base = self._coll_base or {"ops": 0, "wall_s": 0.0, "bytes": 0}
            self._coll_base = totals
            compile_total = float(getattr(
                self._telemetry, "_compile_total", 0.0) or 0.0)
            compile_s = max(0.0, compile_total - self._compile_base)
            self._compile_base = compile_total
            data_s, self._data_wait_s = self._data_wait_s, 0.0
            rec = {
                "gang": self.gang_id or self.collective_group or "local",
                "rank": self.world_rank,
                "world": self.world_size,
                "round": self.step,
                "t": _time.time(),
                "wall_s": round(float(tel.get("step_time_s", 0.0)), 6),
                "data_s": round(data_s, 6),
                "coll_s": round(
                    max(0.0, totals["wall_s"] - base["wall_s"]), 6),
                "coll_bytes": max(0, totals["bytes"] - base["bytes"]),
                "ack_s": round(ack_s, 6),
                "ckpt_s": round(ckpt_s, 6),
                "compile_s": round(compile_s, 6),
                "tokens": metrics.get("tokens"),
                "tps": tel.get("tokens_per_sec"),
                "mfu": tel.get("mfu"),
            }
            gangrec.record_round(rec)
            if data_s > 0:
                _observe_data_wait(data_s)
        except Exception:
            pass

    def _augment_metrics(self, metrics: Dict[str, Any]) -> Dict[str, Any]:
        """Derive goodput numbers for this report round.  Step time is the
        wall clock since the previous report returned (the user's loop
        body); ``tokens``/``flops_per_step`` keys in the reported metrics
        opt into tokens/sec and MFU.  User-provided keys always win."""
        import time as _time

        now = _time.perf_counter()
        prev, self._last_report_t = self._last_report_t, now
        if prev is None:
            return metrics
        try:
            derived = self.telemetry.record_step(
                now - prev,
                tokens=metrics.get("tokens"),
                flops=metrics.get("flops_per_step"),
            )
            for k, v in derived.items():
                metrics.setdefault(k, v)
        except Exception:
            pass  # goodput accounting must never fail a training round
        return metrics

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.restored_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}")
        return _TimedShard(shard, self)

    # ---- called from the actor's polling method -----------------------------

    def next_result(self, timeout: float = 3600.0) -> Optional[dict]:
        try:
            return self.result_queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def ack(self, should_checkpoint: bool = False):
        """Driver's round acknowledgment.  ``should_checkpoint=True``
        carries a drain notice: set BEFORE the semaphore release so the
        rank observes it on its very next should_checkpoint() poll — and,
        because every rank's ack for a round carries the same flag, the
        whole gang saves the SAME step."""
        if should_checkpoint:
            self._drain_pending = True
        self.consumed.release()


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session: this API must be called inside a train loop "
            "launched by a Trainer."
        )
    return _session


def shutdown_session():
    global _session
    _session = None


# ---- public module-level API (mirrors ray.train.*) --------------------------


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_mesh():
    """The jax.sharding.Mesh built from ScalingConfig.mesh for this worker
    (None when the trainer was not configured with a mesh)."""
    return get_session().mesh


def should_checkpoint() -> bool:
    """True when the cluster announced a preemption (node drain) and this
    worker should checkpoint NOW, ahead of its periodic cadence.  Cleared
    by the next report() that carries a checkpoint."""
    return get_session().should_checkpoint()


class TrainContext:
    def get_world_rank(self) -> int:
        return get_session().world_rank

    def get_world_size(self) -> int:
        return get_session().world_size

    def get_trial_dir(self) -> str:
        return get_session().trial_dir


def get_context() -> TrainContext:
    return TrainContext()
