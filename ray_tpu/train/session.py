"""Worker-side train session: report(), get_checkpoint(), rank info, dataset
shards.

Role-equivalent to the reference's per-worker _TrainSession
(reference: train/_internal/session.py — report:403, public report:667,
get_checkpoint:754) with the same synchronous-collective semantics: report()
blocks until the driver has consumed the round, keeping workers in lockstep
(which is exactly what an SPMD TPU job wants).
"""

from __future__ import annotations

import os
import queue
import shutil
import tempfile
import threading
from typing import Any, Dict, Iterator, Optional

from .checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


class TrainSession:
    def __init__(
        self,
        world_rank: int,
        world_size: int,
        trial_dir: str,
        restored_checkpoint: Optional[Checkpoint],
        dataset_shards: Optional[Dict[str, Any]] = None,
    ):
        self.world_rank = world_rank
        self.world_size = world_size
        self.trial_dir = trial_dir
        self.restored_checkpoint = restored_checkpoint
        self.dataset_shards = dataset_shards or {}
        # Set at worker setup when ScalingConfig.mesh is given: the jax Mesh
        # every rank shards its train step over (ray_tpu.train.get_mesh()).
        self.mesh = None
        # Name of the gang's host-side collective group (cross-worker
        # allreduce of metrics/grads outside compiled programs).
        self.collective_group: Optional[str] = None
        self.result_queue: "queue.Queue" = queue.Queue()
        self.consumed = threading.Semaphore(0)
        self.step = 0
        self.finished = False
        # Goodput accounting (train/telemetry.py): report() derives step
        # time / tokens-per-sec / MFU per round and both sets the
        # ray_tpu_train_* gauges and merges the numbers into the reported
        # metrics.  Created lazily so sessions built directly in unit
        # tests don't spin up the metrics flusher.
        self._telemetry = None
        self._last_report_t: Optional[float] = None

    @property
    def telemetry(self):
        if self._telemetry is None:
            from .telemetry import TrainTelemetry

            self._telemetry = TrainTelemetry(rank=self.world_rank)
        return self._telemetry

    # ---- called from user train loop ----------------------------------------

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self.step += 1
        metrics = self._augment_metrics(dict(metrics))
        persisted = None
        if checkpoint is not None:
            # Stage the worker's checkpoint under the trial dir so it outlives
            # the user's temp directory.
            dest = os.path.join(
                self.trial_dir, "staging",
                f"step_{self.step:06d}_rank_{self.world_rank}",
            )
            shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            persisted = dest
        self.result_queue.put(
            {"metrics": metrics, "checkpoint_dir": persisted,
             "step": self.step, "rank": self.world_rank}
        )
        # Lockstep with the driver (reference behavior: session.report blocks
        # until the round is processed).
        self.consumed.acquire()
        # Step time measures the user's loop body, not the driver's round
        # processing: restart the clock after the lockstep wait returns.
        import time as _time

        self._last_report_t = _time.perf_counter()

    def _augment_metrics(self, metrics: Dict[str, Any]) -> Dict[str, Any]:
        """Derive goodput numbers for this report round.  Step time is the
        wall clock since the previous report returned (the user's loop
        body); ``tokens``/``flops_per_step`` keys in the reported metrics
        opt into tokens/sec and MFU.  User-provided keys always win."""
        import time as _time

        now = _time.perf_counter()
        prev, self._last_report_t = self._last_report_t, now
        if prev is None:
            return metrics
        try:
            derived = self.telemetry.record_step(
                now - prev,
                tokens=metrics.get("tokens"),
                flops=metrics.get("flops_per_step"),
            )
            for k, v in derived.items():
                metrics.setdefault(k, v)
        except Exception:
            pass  # goodput accounting must never fail a training round
        return metrics

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.restored_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        shard = self.dataset_shards.get(name)
        if shard is None:
            raise KeyError(f"no dataset shard named {name!r}")
        return shard

    # ---- called from the actor's polling method -----------------------------

    def next_result(self, timeout: float = 3600.0) -> Optional[dict]:
        try:
            return self.result_queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def ack(self):
        self.consumed.release()


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session: this API must be called inside a train loop "
            "launched by a Trainer."
        )
    return _session


def shutdown_session():
    global _session
    _session = None


# ---- public module-level API (mirrors ray.train.*) --------------------------


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_mesh():
    """The jax.sharding.Mesh built from ScalingConfig.mesh for this worker
    (None when the trainer was not configured with a mesh)."""
    return get_session().mesh


class TrainContext:
    def get_world_rank(self) -> int:
        return get_session().world_rank

    def get_world_size(self) -> int:
        return get_session().world_size

    def get_trial_dir(self) -> str:
        return get_session().trial_dir


def get_context() -> TrainContext:
    return TrainContext()
