"""WorkerGroup: a gang of train-worker actors.

Role-equivalent to the reference's WorkerGroup + BackendExecutor
(reference: train/_internal/worker_group.py:102, backend_executor.py:68):
spawns N actors into a placement group, initializes the process group
(jax.distributed analog of _setup_torch_process_group), runs the user train
loop, and relays session reports.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from . import session as session_mod
from .checkpoint import Checkpoint


def _dumps_by_value(fn) -> bytes:
    """Serialize a user train loop so workers never need to import its
    defining module: driver scripts and test files are typically not
    importable from worker processes (pytest imports test files as top-level
    modules; ad-hoc scripts are __main__).  Modules inside installed
    packages keep by-reference semantics."""
    import sys

    mod = sys.modules.get(getattr(fn, "__module__", None))
    by_value = False
    if mod is not None and mod.__name__ not in ("__main__",):
        mod_file = getattr(mod, "__file__", "") or ""
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        in_site = "site-packages" in mod_file or "dist-packages" in mod_file
        in_framework = mod_file.startswith(os.path.join(pkg_dir, ""))
        if mod_file and not in_site and not in_framework:
            try:
                cloudpickle.register_pickle_by_value(mod)
                by_value = True
            except Exception:
                by_value = False
    try:
        return cloudpickle.dumps(fn)
    finally:
        if by_value:
            try:
                cloudpickle.unregister_pickle_by_value(mod)
            except Exception:
                pass


@ray_tpu.remote(max_concurrency=8)
class TrainWorker:
    """One rank of the gang.  max_concurrency lets poll()/ack() — and peer
    snapshot pushes / failure-time snapshot collection — run while the train
    loop blocks inside run()."""

    def __init__(self, rank: int, world_size: int, trial_dir: str):
        self.rank = rank
        self.world_size = world_size
        self.trial_dir = trial_dir
        self.session = None

    def setup(
        self,
        restored_ckpt_path: Optional[str],
        dataset_shards: Optional[Dict[str, Any]],
        collective_group: Optional[str],
        mesh_config=None,
        jax_distributed: bool = False,
        gang_id: str = "",
    ):
        from . import session as smod

        self.session = smod.init_session(
            world_rank=self.rank,
            world_size=self.world_size,
            trial_dir=self.trial_dir,
            restored_checkpoint=(
                Checkpoint(restored_ckpt_path) if restored_ckpt_path else None
            ),
            dataset_shards=dataset_shards,
        )
        self.session.collective_group = collective_group
        # One gang id per WorkerGroup incarnation: the round flight
        # recorder keys its records on it, so a restarted gang's rounds
        # never join against the dead attempt's.
        self.session.gang_id = gang_id or None
        if collective_group is not None:
            from ..collective import init_collective_group

            init_collective_group(
                self.world_size, self.rank, group_name=collective_group
            )
        if jax_distributed and self.world_size > 1:
            # Gang SPMD bootstrap: after this, jax.devices() spans the whole
            # pod and the mesh below is global (reference analog:
            # _setup_torch_process_group runs on every worker in on_start,
            # train/torch/config.py:66-153).  The gang id makes the KV
            # coordinator key unique per WorkerGroup incarnation — a
            # restarted gang must not read the dead attempt's address.
            from ..parallel.distributed import initialize_process_group

            initialize_process_group(
                self.world_size, self.rank,
                group_name=f"{collective_group or 'train'}-{gang_id}",
            )
        if mesh_config is not None:
            from ..parallel.mesh import make_mesh

            self.session.mesh = make_mesh(mesh_config)
        # Rank + host identity: the driver uses node ids to pick each rank's
        # replication peer on a DIFFERENT node where possible.
        return {"rank": self.rank, "node_id": os.environ.get("RT_NODE_ID", "")}

    def configure_memory_checkpoints(self, peer_handle, every_k):
        """Wire this rank's in-memory checkpoint replication: snapshots go
        to the local object store and to ``peer_handle``'s store every K-th
        reported checkpoint (and always on a drain save)."""
        self.session.configure_memory_checkpoints(peer_handle, every_k)
        return True

    def store_peer_snapshot(self, rank: int, step: int, blob: bytes):
        """Receive a peer rank's packed checkpoint: pin it in THIS node's
        object store and remember the handle (last two generations; dropped
        refs free the older replicas)."""
        import ray_tpu

        self.session.remember_snapshot(rank, step, ray_tpu.put(blob))
        return True

    def memory_snapshots(self):
        """{rank: [(step, ObjectRef), ...]} of every in-memory snapshot this
        rank holds (its own + replicas pushed by peers).  Serializing the
        refs to the driver increfs them, so the blobs outlive this worker."""
        return self.session.snapshot_view()

    def run(self, fn_blob: bytes, config: Optional[dict]):
        """Execute the user train loop; always ends with a 'done' sentinel —
        including when the loop fails to even deserialize (the driver polls
        the session queue, so a raised-instead-of-queued error would hang it)."""
        sentinel = {"done": True, "rank": self.rank}
        try:
            fn = cloudpickle.loads(fn_blob)
            if config is not None:
                fn(config)
            else:
                fn()
        except BaseException as e:  # noqa: BLE001 — relayed to the driver
            import traceback

            sentinel["error"] = f"{e}\n{traceback.format_exc()}"
        self.session.finished = True
        # Ship the tail of the round flight recorder BEFORE the done
        # sentinel, synchronously: the driver tears the gang down the
        # moment every loop reports done — faster than the client's 0.5s
        # flush cadence AND faster than a fire-and-forget batch drains —
        # so the last rounds of every run would otherwise only survive
        # in the black box.
        try:
            from ..util import gangrec

            gangrec.flush_rounds(sync=True)
        except Exception:
            pass
        # Same race for the final metrics window: collective-op timings
        # and recorder counters incremented during the last rounds must
        # not die with the actor (bounded: drain_bg times out at 2s).
        try:
            from ..util.metrics import _final_flush

            _final_flush()
        except Exception:
            pass
        self.session.result_queue.put(sentinel)

    def poll(self, timeout: float = 600.0):
        return self.session.next_result(timeout=timeout)

    def ack(self, should_checkpoint: bool = False):
        self.session.ack(should_checkpoint)
        return True

    def _init_collective(self, world_size, rank, group_name):
        from ..collective import init_collective_group

        init_collective_group(world_size, rank, group_name=group_name)


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 trial_dir: str, placement_strategy: str = "PACK",
                 mesh_config=None, jax_distributed: bool = False,
                 runtime_env: Optional[dict] = None,
                 memory_ckpt_every_k: Optional[int] = None):
        self.num_workers = num_workers
        self.trial_dir = trial_dir
        self.mesh_config = mesh_config
        self.jax_distributed = jax_distributed
        self.runtime_env = runtime_env
        # <=0 means disabled, same as None (0 would ZeroDivision in the
        # session's cadence check; negative cadences are meaningless).
        self.memory_ckpt_every_k = (
            memory_ckpt_every_k
            if memory_ckpt_every_k and memory_ckpt_every_k > 0 else None
        )
        self.gang_nodes: set = set()  # filled by setup()
        self.gang_id = os.urandom(4).hex()
        self.pg = None
        if num_workers > 1:
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                pg = ray_tpu.placement_group(
                    [dict(resources_per_worker) for _ in range(num_workers)],
                    strategy=placement_strategy,
                )
            if pg.infeasible_now:
                # Bundles don't fit this cluster: a pending PG would park the
                # whole gang forever — drop it and schedule best-effort.
                ray_tpu.remove_placement_group(pg)
            else:
                self.pg = pg
        opts: Dict[str, Any] = {"num_cpus": resources_per_worker.get("CPU", 1)}
        if resources_per_worker.get("TPU"):
            opts["num_tpus"] = resources_per_worker["TPU"]
        if runtime_env:
            opts["runtime_env"] = runtime_env
        self.workers: List[Any] = []
        for rank in range(num_workers):
            cls = TrainWorker
            if self.pg is not None:
                cls = TrainWorker.options(
                    scheduling_strategy=ray_tpu.PlacementGroupSchedulingStrategy(
                        self.pg, rank
                    ),
                    **opts,
                )
            else:
                cls = TrainWorker.options(**opts)
            self.workers.append(
                cls.remote(rank, num_workers,
                           os.path.join(trial_dir, f"rank_{rank}"))
            )

    def setup(self, restored_ckpt: Optional[str],
              dataset_shards: Optional[List[Dict[str, Any]]],
              collective_group: Optional[str]):
        refs = [
            w.setup.remote(
                restored_ckpt,
                dataset_shards[i] if dataset_shards else None,
                collective_group,
                self.mesh_config,
                self.jax_distributed,
                self.gang_id,
            )
            for i, w in enumerate(self.workers)
        ]
        infos = ray_tpu.get(refs)
        # Which cluster nodes host this gang (hex ids) — the trainer
        # filters drain notices against this set.
        self.gang_nodes = {i.get("node_id", "") for i in infos} - {""}
        if self.memory_ckpt_every_k is not None and self.num_workers > 1:
            self._wire_replication_peers(infos)
        return infos

    def _wire_replication_peers(self, infos: List[dict]):
        """Give each rank a replication peer: the nearest ring successor on
        a DIFFERENT node when one exists (with PACK placement, consecutive
        ranks co-locate — a same-node ring neighbor would die with the rank
        it is supposed to back up), else the plain ring successor."""
        nodes = {i["rank"]: i.get("node_id", "") for i in infos}
        n = self.num_workers
        refs = []
        for r in range(n):
            peer = (r + 1) % n
            for off in range(1, n):
                cand = (r + off) % n
                if nodes.get(cand) and nodes.get(cand) != nodes.get(r):
                    peer = cand
                    break
            refs.append(self.workers[r].configure_memory_checkpoints.remote(
                self.workers[peer], self.memory_ckpt_every_k
            ))
        ray_tpu.get(refs)

    def start_training(self, fn: Callable, config: Optional[dict]):
        blob = _dumps_by_value(fn)
        self.run_refs = [w.run.remote(blob, config) for w in self.workers]

    def poll_all(self, ranks: Optional[List[int]] = None,
                 timeout: float = 600.0) -> List[dict]:
        targets = (
            self.workers if ranks is None else [self.workers[r] for r in ranks]
        )
        return ray_tpu.get(
            [w.poll.remote(timeout) for w in targets],
            timeout=timeout + 60,
        )

    def ack_all(self, ranks: Optional[List[int]] = None,
                should_checkpoint: bool = False):
        """Release the round's lockstep.  ``should_checkpoint=True`` relays
        a drain notice to every acked rank at the same round boundary."""
        targets = (
            self.workers if ranks is None else [self.workers[r] for r in ranks]
        )
        ray_tpu.get([w.ack.remote(should_checkpoint) for w in targets])

    def collect_memory_snapshots(self, timeout: float = 5.0):
        """Gather in-memory checkpoint replicas from the surviving workers
        after a gang failure (call BEFORE shutdown()).  Returns
        ``(step, {rank: packed_dir_blob})`` for the newest step with full
        rank coverage, or None when no complete in-memory set survived
        (e.g. consecutive co-located ranks died with their replicas)."""
        import time as _time

        avail: Dict[int, Dict[int, Any]] = {}  # rank -> {step: ref}
        # Fan out first, then collect against ONE shared deadline: dead
        # ranks burn the timeout concurrently instead of serially stalling
        # the recovery path (each get charges only the time remaining).
        calls = [w.memory_snapshots.remote() for w in self.workers]
        deadline = _time.monotonic() + timeout
        for ref in calls:
            try:
                snaps = ray_tpu.get(
                    ref, timeout=max(0.2, deadline - _time.monotonic())
                )
            except Exception:
                continue  # dead or unreachable rank: its peers cover it
            for rank, entries in snaps.items():
                for step, ref in entries:
                    avail.setdefault(rank, {})[step] = ref
        if len(avail) < self.num_workers:
            return None  # some rank left no surviving replica at all
        # Newest step EVERY rank has a snapshot for (ranks may be one round
        # apart when a node dies mid-round; two kept generations guarantee
        # an intersection when replication ran on consecutive rounds).
        common = set.intersection(
            *(set(steps) for steps in avail.values())
        )
        if not common:
            return None
        best = max(common)
        blobs: Dict[int, bytes] = {}
        deadline = _time.monotonic() + timeout
        for rank in range(self.num_workers):
            try:
                blobs[rank] = ray_tpu.get(
                    avail[rank][best],
                    timeout=max(0.2, deadline - _time.monotonic()),
                )
            except Exception:
                return None  # replica's store node died too
        return best, blobs

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                ray_tpu.remove_placement_group(self.pg)
            except Exception:
                pass
