"""WorkerGroup: a gang of train-worker actors.

Role-equivalent to the reference's WorkerGroup + BackendExecutor
(reference: train/_internal/worker_group.py:102, backend_executor.py:68):
spawns N actors into a placement group, initializes the process group
(jax.distributed analog of _setup_torch_process_group), runs the user train
loop, and relays session reports.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from . import session as session_mod
from .checkpoint import Checkpoint


def _dumps_by_value(fn) -> bytes:
    """Serialize a user train loop so workers never need to import its
    defining module: driver scripts and test files are typically not
    importable from worker processes (pytest imports test files as top-level
    modules; ad-hoc scripts are __main__).  Modules inside installed
    packages keep by-reference semantics."""
    import sys

    mod = sys.modules.get(getattr(fn, "__module__", None))
    by_value = False
    if mod is not None and mod.__name__ not in ("__main__",):
        mod_file = getattr(mod, "__file__", "") or ""
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        in_site = "site-packages" in mod_file or "dist-packages" in mod_file
        in_framework = mod_file.startswith(os.path.join(pkg_dir, ""))
        if mod_file and not in_site and not in_framework:
            try:
                cloudpickle.register_pickle_by_value(mod)
                by_value = True
            except Exception:
                by_value = False
    try:
        return cloudpickle.dumps(fn)
    finally:
        if by_value:
            try:
                cloudpickle.unregister_pickle_by_value(mod)
            except Exception:
                pass


@ray_tpu.remote(max_concurrency=4)
class TrainWorker:
    """One rank of the gang.  max_concurrency lets poll()/ack() run while the
    train loop blocks inside run()."""

    def __init__(self, rank: int, world_size: int, trial_dir: str):
        self.rank = rank
        self.world_size = world_size
        self.trial_dir = trial_dir
        self.session = None

    def setup(
        self,
        restored_ckpt_path: Optional[str],
        dataset_shards: Optional[Dict[str, Any]],
        collective_group: Optional[str],
        mesh_config=None,
        jax_distributed: bool = False,
        gang_id: str = "",
    ):
        from . import session as smod

        self.session = smod.init_session(
            world_rank=self.rank,
            world_size=self.world_size,
            trial_dir=self.trial_dir,
            restored_checkpoint=(
                Checkpoint(restored_ckpt_path) if restored_ckpt_path else None
            ),
            dataset_shards=dataset_shards,
        )
        self.session.collective_group = collective_group
        if collective_group is not None:
            from ..collective import init_collective_group

            init_collective_group(
                self.world_size, self.rank, group_name=collective_group
            )
        if jax_distributed and self.world_size > 1:
            # Gang SPMD bootstrap: after this, jax.devices() spans the whole
            # pod and the mesh below is global (reference analog:
            # _setup_torch_process_group runs on every worker in on_start,
            # train/torch/config.py:66-153).  The gang id makes the KV
            # coordinator key unique per WorkerGroup incarnation — a
            # restarted gang must not read the dead attempt's address.
            from ..parallel.distributed import initialize_process_group

            initialize_process_group(
                self.world_size, self.rank,
                group_name=f"{collective_group or 'train'}-{gang_id}",
            )
        if mesh_config is not None:
            from ..parallel.mesh import make_mesh

            self.session.mesh = make_mesh(mesh_config)
        return self.rank

    def run(self, fn_blob: bytes, config: Optional[dict]):
        """Execute the user train loop; always ends with a 'done' sentinel —
        including when the loop fails to even deserialize (the driver polls
        the session queue, so a raised-instead-of-queued error would hang it)."""
        try:
            fn = cloudpickle.loads(fn_blob)
            if config is not None:
                fn(config)
            else:
                fn()
            self.session.result_queue.put({"done": True, "rank": self.rank})
        except BaseException as e:  # noqa: BLE001 — relayed to the driver
            import traceback

            self.session.result_queue.put({
                "done": True, "rank": self.rank,
                "error": f"{e}\n{traceback.format_exc()}",
            })
        finally:
            self.session.finished = True

    def poll(self, timeout: float = 600.0):
        return self.session.next_result(timeout=timeout)

    def ack(self):
        self.session.ack()
        return True

    def _init_collective(self, world_size, rank, group_name):
        from ..collective import init_collective_group

        init_collective_group(world_size, rank, group_name=group_name)


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 trial_dir: str, placement_strategy: str = "PACK",
                 mesh_config=None, jax_distributed: bool = False,
                 runtime_env: Optional[dict] = None):
        self.num_workers = num_workers
        self.trial_dir = trial_dir
        self.mesh_config = mesh_config
        self.jax_distributed = jax_distributed
        self.runtime_env = runtime_env
        self.gang_id = os.urandom(4).hex()
        self.pg = None
        if num_workers > 1:
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                pg = ray_tpu.placement_group(
                    [dict(resources_per_worker) for _ in range(num_workers)],
                    strategy=placement_strategy,
                )
            if pg.infeasible_now:
                # Bundles don't fit this cluster: a pending PG would park the
                # whole gang forever — drop it and schedule best-effort.
                ray_tpu.remove_placement_group(pg)
            else:
                self.pg = pg
        opts: Dict[str, Any] = {"num_cpus": resources_per_worker.get("CPU", 1)}
        if resources_per_worker.get("TPU"):
            opts["num_tpus"] = resources_per_worker["TPU"]
        if runtime_env:
            opts["runtime_env"] = runtime_env
        self.workers: List[Any] = []
        for rank in range(num_workers):
            cls = TrainWorker
            if self.pg is not None:
                cls = TrainWorker.options(
                    scheduling_strategy=ray_tpu.PlacementGroupSchedulingStrategy(
                        self.pg, rank
                    ),
                    **opts,
                )
            else:
                cls = TrainWorker.options(**opts)
            self.workers.append(
                cls.remote(rank, num_workers,
                           os.path.join(trial_dir, f"rank_{rank}"))
            )

    def setup(self, restored_ckpt: Optional[str],
              dataset_shards: Optional[List[Dict[str, Any]]],
              collective_group: Optional[str]):
        refs = [
            w.setup.remote(
                restored_ckpt,
                dataset_shards[i] if dataset_shards else None,
                collective_group,
                self.mesh_config,
                self.jax_distributed,
                self.gang_id,
            )
            for i, w in enumerate(self.workers)
        ]
        return ray_tpu.get(refs)

    def start_training(self, fn: Callable, config: Optional[dict]):
        blob = _dumps_by_value(fn)
        self.run_refs = [w.run.remote(blob, config) for w in self.workers]

    def poll_all(self, ranks: Optional[List[int]] = None,
                 timeout: float = 600.0) -> List[dict]:
        targets = (
            self.workers if ranks is None else [self.workers[r] for r in ranks]
        )
        return ray_tpu.get(
            [w.poll.remote(timeout) for w in targets],
            timeout=timeout + 60,
        )

    def ack_all(self, ranks: Optional[List[int]] = None):
        targets = (
            self.workers if ranks is None else [self.workers[r] for r in ranks]
        )
        ray_tpu.get([w.ack.remote() for w in targets])

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        if self.pg is not None:
            try:
                ray_tpu.remove_placement_group(self.pg)
            except Exception:
                pass
