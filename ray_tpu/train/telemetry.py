"""Train goodput accounting: step time, tokens/sec, compile time, and MFU.

Role-equivalent to the telemetry TorchTitan treats as table stakes for LLM
training (arXiv:2410.06511 — per-step wall time, throughput in tokens/sec,
and model-flops utilization against the accelerator's peak), surfaced here
as ``ray_tpu_train_*`` gauges (flowing to the head's metrics plane and the
dashboard's history sparklines) and merged into ``train.session.report``
metrics.

MFU = (model FLOPs per step) / (step seconds) / (peak FLOP/s of the
devices the step ran on).  FLOPs per step come from XLA's own cost model
(``jax.jit(fn).lower(*args).cost_analysis()["flops"]``) when available,
else from the classic dense-transformer estimate ``6 * params * tokens``
(``transformer_flops``), else from an explicit number the caller provides.
CPU backends get a nominal peak so MFU stays finite and tests run
everywhere — the absolute value is meaningless off-accelerator, the
*trend* is still useful.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Optional

#: Per-device peak dense FLOP/s (bf16) by device-kind substring, checked in
#: order.  Sources: published TPU/GPU spec sheets.
PEAK_FLOPS_TABLE = (
    # jax device_kind spells the lite parts "TPU v5 lite" / "TPU v6 lite".
    ("v6 lite", 918e12),  # TPU v6e (Trillium)
    ("v6e", 918e12),
    ("v5 lite", 197e12),  # TPU v5e
    ("v5litepod", 197e12),
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),  # bare "TPU v5" device_kind: the p part
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("h100", 989e12),
    ("a100", 312e12),
)

#: Nominal per-core peak for CPU backends: keeps MFU finite in CPU-only
#: smoke runs (the stub the issue calls for); not a real utilization.
CPU_NOMINAL_PEAK_FLOPS = 1e11


def device_peak_flops(device: Optional[Any] = None) -> float:
    """Peak FLOP/s of one device (``jax.devices()[0]`` when omitted).
    Unknown accelerators fall back to the CPU nominal rather than raising —
    a telemetry path must never kill a train step."""
    kind = ""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        kind = (getattr(device, "device_kind", "") or "").lower()
    except Exception:
        return CPU_NOMINAL_PEAK_FLOPS
    for sub, peak in PEAK_FLOPS_TABLE:
        if sub in kind:
            return peak
    return CPU_NOMINAL_PEAK_FLOPS


def flops_per_step(fn, *args, **kwargs) -> Optional[float]:
    """Model FLOPs of one call of ``fn(*args, **kwargs)`` via XLA's cost
    analysis (reference technique: ``jax.jit(...).lower().cost_analysis()``;
    TorchTitan derives the same number analytically).  Returns None when the
    backend provides no cost model — callers fall back to
    ``transformer_flops`` or an explicit value."""
    try:
        import jax

        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        lowered = jitted.lower(*args, **kwargs)
        try:
            analysis = lowered.cost_analysis()  # no compile needed
        except Exception:
            analysis = lowered.compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):  # older jax returns [dict]
            analysis = analysis[0] if analysis else None
        if analysis:
            f = analysis.get("flops")
            if isinstance(f, (int, float)) and f > 0:
                return float(f)
    except Exception:
        pass
    return None


def transformer_flops(num_params: float, tokens: float) -> float:
    """Static fallback: the standard dense-transformer training estimate of
    ~6 FLOPs per parameter per token (fwd 2 + bwd 4)."""
    return 6.0 * float(num_params) * float(tokens)


class TrainTelemetry:
    """Per-process goodput recorder.  One instance per train worker (the
    session owns one); gauges flow to the head via the metrics flusher.

    ``flops_per_step`` and ``peak_flops`` may be set up front (or any time)
    so subsequent steps compute MFU; ``tokens_per_step`` likewise enables
    tokens/sec without passing tokens on every call."""

    def __init__(self, flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 num_devices: Optional[int] = None,
                 tokens_per_step: Optional[float] = None,
                 rank: Optional[int] = None):
        from ..util.metrics import get_gauge

        self.flops_per_step = flops_per_step
        self._peak_flops = peak_flops
        self._num_devices = num_devices
        self.tokens_per_step = tokens_per_step
        # Rank tag keeps each train worker's gauges a distinct series —
        # the head merges same-(name, tags) gauges last-writer-wins, so
        # untagged multi-worker gauges would flip between ranks.
        self._tags = {"rank": str(rank)} if rank is not None else None
        self.last: Dict[str, float] = {}
        self._g_step = get_gauge(
            "ray_tpu_train_step_seconds", "Wall time of the last train step",
            tag_keys=("rank",))
        self._g_tps = get_gauge(
            "ray_tpu_train_tokens_per_sec",
            "Training throughput of the last step", tag_keys=("rank",))
        self._g_mfu = get_gauge(
            "ray_tpu_train_mfu",
            "Model-flops utilization of the last step (0..1)",
            tag_keys=("rank",))
        self._g_compile = get_gauge(
            "ray_tpu_train_compile_seconds",
            "Cumulative compile/tracing seconds observed by this worker",
            tag_keys=("rank",))
        self._compile_total = 0.0

    # -- configuration ---------------------------------------------------------

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        self.flops_per_step = flops

    def peak_flops_total(self) -> float:
        """Aggregate peak FLOP/s across the devices this step runs on."""
        peak = self._peak_flops
        if peak is None:
            peak = device_peak_flops()
        n = self._num_devices
        if n is None:
            try:
                import jax

                n = jax.local_device_count()
            except Exception:
                n = 1
        return peak * max(1, n)

    # -- recording -------------------------------------------------------------

    def record_compile(self, seconds: float) -> None:
        self._compile_total += max(0.0, seconds)
        self._g_compile.set(self._compile_total, tags=self._tags)
        self.last["compile_time_s"] = seconds

    def record_step(self, step_time_s: float,
                    tokens: Optional[float] = None,
                    flops: Optional[float] = None,
                    compile_time_s: Optional[float] = None
                    ) -> Dict[str, float]:
        """Record one finished step; returns the derived metrics
        ({step_time_s, tokens_per_sec?, mfu?, compile_time_s?})."""
        out: Dict[str, float] = {"step_time_s": float(step_time_s)}
        self._g_step.set(step_time_s, tags=self._tags)
        if compile_time_s is not None:
            self.record_compile(compile_time_s)
            out["compile_time_s"] = compile_time_s
        tokens = tokens if tokens is not None else self.tokens_per_step
        if tokens and step_time_s > 0:
            out["tokens_per_sec"] = tokens / step_time_s
            self._g_tps.set(out["tokens_per_sec"], tags=self._tags)
        flops = flops if flops is not None else self.flops_per_step
        if flops and step_time_s > 0:
            mfu = flops / step_time_s / self.peak_flops_total()
            out["mfu"] = mfu
            self._g_mfu.set(mfu, tags=self._tags)
        self.last = dict(out)
        return out

    @contextlib.contextmanager
    def step(self, tokens: Optional[float] = None,
             flops: Optional[float] = None):
        """Time a train step: ``with telemetry.step(tokens=...): ...``."""
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self.record_step(time.perf_counter() - t0,
                             tokens=tokens, flops=flops)
