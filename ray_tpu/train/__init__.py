"""ray_tpu.train: distributed training orchestration (reference: ray.train).

The worker gang is actor-based like the reference, but the data plane is
jax/pjit: instead of wrapping models in DDP/FSDP, a ScalingConfig carries a
MeshConfig and models shard via ShardingRules (ray_tpu.models.make_train_step).
"""

from .checkpoint import (
    AsyncCheckpointWriter,
    Checkpoint,
    CheckpointManager,
    load_pytree,
    save_pytree,
)
from .config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from . import telemetry
from .session import (
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_mesh,
    report,
    should_checkpoint,
)
from .telemetry import TrainTelemetry
from .trainer import DataParallelTrainer, JaxTrainer, TrainingFailedError

__all__ = [
    "Checkpoint", "CheckpointManager", "save_pytree", "load_pytree",
    "AsyncCheckpointWriter",
    "RunConfig", "ScalingConfig", "FailureConfig", "CheckpointConfig",
    "Result", "report", "get_checkpoint", "get_context", "get_dataset_shard",
    "get_mesh", "should_checkpoint",
    "DataParallelTrainer", "JaxTrainer", "TrainingFailedError",
    "telemetry", "TrainTelemetry",
]
