"""Train configuration dataclasses.

Role-equivalent to the reference's air/config.py (RunConfig, ScalingConfig,
FailureConfig, CheckpointConfig) and air/result.py (Result) — with the
TPU-first difference that ScalingConfig describes a device mesh per worker
(dp/fsdp/tp/sp) instead of GPU counts, making DP→FSDP→TP/SP a config change
rather than new wrapper code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from ..parallel.mesh import MeshConfig


@dataclasses.dataclass
class ScalingConfig:
    """How many framework workers, with what resources, and how each worker's
    devices form a mesh (reference: air/config.py ScalingConfig)."""

    num_workers: int = 1
    # Elastic floor: after a failure that shrank the cluster (e.g. a
    # preempted node not yet replaced), the trainer re-forms the gang at the
    # largest feasible world size within [min_workers, num_workers] instead
    # of waiting for full capacity, and grows back toward num_workers on a
    # later restart once the autoscaler backfills.  None = not elastic
    # (always num_workers — the reference's fixed-size semantics).
    min_workers: Optional[int] = None
    # How long a restart may wait for at least min_workers' worth of
    # capacity to appear before giving up (elastic gangs only).
    elastic_wait_s: float = 30.0
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    # Mesh built in every worker at setup (exposed via
    # ray_tpu.train.get_mesh()): over the worker's local devices, or over the
    # whole pod when jax_distributed bootstraps first (multi-host gang).
    mesh: Optional[MeshConfig] = None
    # Run jax.distributed.initialize across the gang before building the
    # mesh (the analog of _setup_torch_process_group, reference:
    # train/torch/config.py:66).  None = auto: multi-worker TPU gangs only
    # (multi-process CPU meshes aren't supported by JAX).
    jax_distributed: Optional[bool] = None
    # Per-worker runtime env (e.g. env_vars setting XLA flags).
    runtime_env: Optional[dict] = None
    placement_strategy: str = "PACK"

    def wants_jax_distributed(self) -> bool:
        if self.jax_distributed is not None:
            return self.jax_distributed
        return self.use_tpu and self.num_workers > 1

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        if self.use_tpu:
            res.setdefault("TPU", 1.0)
        return res


@dataclasses.dataclass
class FailureConfig:
    """(reference: air/config.py FailureConfig) — max_failures < 0 means
    unlimited restarts."""

    max_failures: int = 0


@dataclasses.dataclass
class CheckpointConfig:
    """(reference: air/config.py CheckpointConfig)"""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # "max" | "min"
    # Peer-replicated in-memory checkpoints: every K-th reported checkpoint,
    # each rank also pushes its host snapshot into a surviving peer's object
    # store (ring-neighbor, different-node preferred).  After a failure the
    # new gang restores from the freshest in-memory copy when it is newer
    # than the last disk write — recovery costs seconds, not a checkpoint
    # interval (TorchTitan-style replicated in-memory checkpoints).
    # OPT-IN (None disables): replication packs the whole checkpoint into
    # host memory and does a confirmed cross-node push inside the report
    # path — a price multi-GB checkpoints must choose, not inherit.
    memory_ckpt_every_k: Optional[int] = None
    # Disk-persistence cadence among reported checkpoints: the trainer
    # registers every K-th reported checkpoint into durable storage (drain
    # saves always persist).  With frequent cheap host snapshots + sparse
    # disk writes, an un-announced failure recovers from the in-memory
    # replicas at a step strictly later than the last disk checkpoint.
    disk_ckpt_every_k: int = 1


@dataclasses.dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: Optional[FailureConfig] = None
    checkpoint_config: Optional[CheckpointConfig] = None


@dataclasses.dataclass
class Result:
    """(reference: air/result.py Result)"""

    metrics: Dict[str, Any]
    checkpoint: Optional["Checkpoint"]  # noqa: F821
    path: str
    error: Optional[BaseException] = None
    metrics_history: Optional[list] = None

    @property
    def best_checkpoints(self):
        return self._best_checkpoints

    _best_checkpoints: list = dataclasses.field(default_factory=list)
