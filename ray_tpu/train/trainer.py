"""DataParallelTrainer: drive a gang of train workers to completion.

Role-equivalent to the reference's DataParallelTrainer.training_loop over a
BackendExecutor (reference: train/data_parallel_trainer.py:25,428;
_internal/backend_executor.py:135,451,578), with elastic restart from the
latest checkpoint on worker failure (FailureConfig — reference:
backend_executor worker-group restart semantics).
"""

from __future__ import annotations

import os
import shutil
import tempfile
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ..exceptions import ActorDiedError, RayTpuError, WorkerCrashedError
from .checkpoint import Checkpoint, CheckpointManager
from .config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .worker_group import WorkerGroup


class TrainingFailedError(RayTpuError):
    pass


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on `scaling_config.num_workers` actors.

    The worker loop uses ray_tpu.train.report/get_checkpoint/
    get_dataset_shard — same shape as the reference's ray.train API.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        # Optional hook: called with each report round's metrics (the Tuner
        # bridges this to tune.report so ASHA can early-stop trainer trials).
        self._report_callback = None

    # ------------------------------------------------------------------ fit

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        name = self.run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        storage = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results"
        )
        trial_dir = os.path.join(storage, name)
        os.makedirs(trial_dir, exist_ok=True)

        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        manager = CheckpointManager(
            os.path.join(trial_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        fail_cfg = self.run_config.failure_config or FailureConfig()
        failures = 0
        restore = self.resume_from_checkpoint
        last_metrics: Dict[str, Any] = {}
        history: List[dict] = []
        error: Optional[BaseException] = None

        while True:
            group = WorkerGroup(
                self.scaling_config.num_workers,
                self.scaling_config.worker_resources(),
                trial_dir,
                self.scaling_config.placement_strategy,
                mesh_config=self.scaling_config.mesh,
                jax_distributed=self.scaling_config.wants_jax_distributed(),
                runtime_env=self.scaling_config.runtime_env,
            )
            try:
                shards = self._make_dataset_shards()
                group.setup(
                    restore.path if restore else None,
                    shards,
                    collective_group=f"train-{name}",
                )
                group.start_training(self.train_loop, self.train_loop_config)
                last_metrics, history_part = self._drive(group, manager)
                history.extend(history_part)
                error = None
                break
            except (WorkerCrashedError, ActorDiedError, ray_tpu.exceptions.RayTpuError) as e:
                failures += 1
                history_part = getattr(e, "_history", [])
                history.extend(history_part)
                if fail_cfg.max_failures >= 0 and failures > fail_cfg.max_failures:
                    error = TrainingFailedError(
                        f"training failed after {failures} failure(s): {e}"
                    )
                    break
                restore = manager.latest() or self.resume_from_checkpoint
            finally:
                group.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=manager.latest(),
            path=trial_dir,
            error=error,
            metrics_history=history,
        )

    # ---------------------------------------------------------------- drive

    def _drive(self, group: WorkerGroup, manager: CheckpointManager):
        """Poll report rounds until every worker finishes
        (reference: backend_executor.get_next_results:578)."""
        last_metrics: Dict[str, Any] = {}
        history: List[dict] = []
        done = [False] * group.num_workers
        while not all(done):
            active = [r for r in range(group.num_workers) if not done[r]]
            results = group.poll_all(active)
            reports = []
            for r in results:
                if r is None:
                    raise TrainingFailedError("worker poll timed out")
                if r.get("done"):
                    done[r["rank"]] = True
                    if r.get("error"):
                        err = TrainingFailedError(
                            f"rank {r['rank']} failed:\n{r['error']}"
                        )
                        err._history = history
                        raise err
                else:
                    reports.append(r)
            if reports:
                rank0 = next((r for r in reports if r["rank"] == 0), reports[0])
                metrics = rank0["metrics"]
                ckpt_dirs = [r["checkpoint_dir"] for r in reports
                             if r.get("checkpoint_dir")]
                if ckpt_dirs:
                    merged = self._merge_checkpoints(ckpt_dirs)
                    manager.register(Checkpoint(merged), metrics)
                    shutil.rmtree(merged, ignore_errors=True)
                    for d in ckpt_dirs:
                        shutil.rmtree(d, ignore_errors=True)
                last_metrics = metrics
                history.append(metrics)
                if self._report_callback is not None:
                    self._report_callback(metrics)
                group.ack_all([r["rank"] for r in reports])
        return last_metrics, history

    @staticmethod
    def _merge_checkpoints(dirs: List[str]) -> str:
        """Merge per-rank checkpoint dirs (rank files must be distinct or
        identical; rank 0 wins collisions by being copied last)."""
        merged = tempfile.mkdtemp(prefix="rt_merged_ckpt_")
        for d in sorted(dirs, reverse=True):
            shutil.copytree(d, merged, dirs_exist_ok=True)
        return merged

    def _make_dataset_shards(self) -> Optional[List[Dict[str, Any]]]:
        if not self.datasets:
            return None
        n = self.scaling_config.num_workers
        per_worker: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for dname, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                shards = ds.streaming_split(n)
            elif isinstance(ds, (list, tuple)):
                shards = [list(ds[i::n]) for i in range(n)]
            else:
                shards = [ds] * n  # replicated (caller shards inside loop)
            for i in range(n):
                per_worker[i][dname] = shards[i]
        return per_worker


class JaxTrainer(DataParallelTrainer):
    """Alias emphasizing the JAX-native path (the reference's TorchTrainer
    analog — train/torch/torch_trainer.py:11)."""
