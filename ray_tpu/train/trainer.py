"""DataParallelTrainer: drive a gang of train workers to completion.

Role-equivalent to the reference's DataParallelTrainer.training_loop over a
BackendExecutor (reference: train/data_parallel_trainer.py:25,428;
_internal/backend_executor.py:135,451,578), with elastic restart from the
latest checkpoint on worker failure (FailureConfig — reference:
backend_executor worker-group restart semantics).
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ..exceptions import ActorDiedError, RayTpuError, WorkerCrashedError
from .checkpoint import Checkpoint, CheckpointManager
from .config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from .worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingFailedError(RayTpuError):
    pass


# Guards every set/consume of a trainer's one-shot _drain_requested flag
# (pubsub thread vs drive loop).  Module-level, not per-instance: trainers
# must stay picklable (the Tuner ships them to trial actors), and the
# critical sections are two-instruction swaps — coarse sharing is free.
_drain_flag_lock = threading.Lock()


def _quiet_demand_pg(resources: Dict[str, float], bundles: int):
    """Best-effort demand signal: a placement group of ``bundles`` worker-
    shaped bundles, created without the may-not-fit warning (not fitting is
    the point — pending PGs are what the autoscaler scales against).
    Returns None on failure."""
    try:
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return ray_tpu.placement_group(
                [dict(resources) for _ in range(bundles)]
            )
    except Exception:
        return None


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on `scaling_config.num_workers` actors.

    The worker loop uses ray_tpu.train.report/get_checkpoint/
    get_dataset_shard — same shape as the reference's ray.train API.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint
        # Optional hook: called with each report round's metrics (the Tuner
        # bridges this to tune.report so ASHA can early-stop trainer trials).
        self._report_callback = None
        # World size of the CURRENT gang incarnation (elastic restarts may
        # run below num_workers) and the session step of the last disk
        # checkpoint this incarnation (memory-replica freshness gate).
        self.world_size = self.scaling_config.num_workers
        self._last_disk_ckpt_step = 0
        self._ckpt_rounds = 0
        self._disk_every_k = 1
        # Driver-observed preemption notice for the CURRENT gang: set by the
        # node_events subscription (installed for the duration of fit(),
        # removed after — a leaked handler would pin this trainer forever),
        # relayed to every rank on the same lockstep ack so the whole gang
        # drain-saves the same step.
        self._drain_requested = False
        self._gang_nodes: set = set()
        self._drain_handler = None
        # Newest disk-skipped checkpoint round, held on the driver's disk
        # as (step, merged_dir, metrics) until a newer round persists.
        self._pending_skipped = None
        # Standing demand for the capacity a downsized gang is missing
        # (num_workers - world bundles): the autoscaler backfills against
        # it so the next restart can upsize.  Removed before capacity
        # measurement and at fit() exit.
        self._backfill_pg = None

    # ------------------------------------------------------------------ fit

    def _install_drain_subscription(self) -> None:
        """Listen for head-announced node drains (preemption notices).  A
        drain of any node hosting a gang member flips _drain_requested; the
        drive loop relays it on the next round's acks, so every rank's
        should_checkpoint() flips at the SAME step (per-rank pubsub would
        skew ranks by a round and persist partial-rank checkpoints)."""
        if self._drain_handler is not None:
            return
        from ..core.context import ctx

        if ctx.client is None:
            return

        def on_event(data):
            if not (isinstance(data, dict) and data.get("event") == "drain"):
                return
            # Unknown gang membership (empty set) counts as relevant —
            # better a spurious checkpoint than a missed grace window.
            if self._gang_nodes and data.get("node_id") not in self._gang_nodes:
                return
            with _drain_flag_lock:
                self._drain_requested = True

        ctx.client.subscribe("node_events", on_event)
        self._drain_handler = on_event

    def _consume_drain_notice(self) -> bool:
        """Atomically read-and-clear the one-shot drain notice: a lock-free
        swap could overwrite a notice the pubsub thread set mid-swap, and
        node_drain publishes exactly once per node."""
        with _drain_flag_lock:
            drain, self._drain_requested = self._drain_requested, False
            return drain

    def _remove_drain_subscription(self) -> None:
        handler, self._drain_handler = self._drain_handler, None
        if handler is None:
            return
        from ..core.context import ctx

        try:
            if ctx.client is not None:
                ctx.client.unsubscribe("node_events", handler)
        except Exception:
            pass

    def fit(self) -> Result:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        try:
            self._install_drain_subscription()
        except Exception:
            pass  # drain relay is an optimization, never a fit() blocker
        try:
            return self._fit()
        finally:
            # Handler removal, not just dedup: a leaked closure would keep
            # this trainer reachable and fire on every future drain.
            self._remove_drain_subscription()
            self._clear_backfill_demand()

    def _fit(self) -> Result:
        name = self.run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        storage = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results"
        )
        trial_dir = os.path.join(storage, name)
        os.makedirs(trial_dir, exist_ok=True)

        ckpt_cfg = self.run_config.checkpoint_config or CheckpointConfig()
        manager = CheckpointManager(
            os.path.join(trial_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            score_attribute=ckpt_cfg.checkpoint_score_attribute,
            score_order=ckpt_cfg.checkpoint_score_order,
        )
        fail_cfg = self.run_config.failure_config or FailureConfig()
        failures = 0
        restore = self.resume_from_checkpoint
        last_metrics: Dict[str, Any] = {}
        history: List[dict] = []
        error: Optional[BaseException] = None

        while True:
            try:
                world = self._resolve_world_size(settle=failures > 0)
            except TrainingFailedError as e:
                error = e
                break
            self.world_size = world
            # Per-incarnation step bookkeeping: session steps restart at 0
            # with each gang, so disk-vs-memory freshness is only compared
            # within one incarnation.
            self._last_disk_ckpt_step = 0
            self._ckpt_rounds = 0
            self._disk_every_k = max(1, ckpt_cfg.disk_ckpt_every_k)
            self._drop_pending_skipped()
            # Drop any notice consumed by (or aimed at) the PREVIOUS gang
            # before the new one forms; events landing from here on are
            # accepted conservatively (empty gang set = relevant).
            self._gang_nodes = set()
            self._consume_drain_notice()
            group = WorkerGroup(
                world,
                self.scaling_config.worker_resources(),
                trial_dir,
                self.scaling_config.placement_strategy,
                mesh_config=self.scaling_config.mesh,
                jax_distributed=self.scaling_config.wants_jax_distributed(),
                runtime_env=self.scaling_config.runtime_env,
                memory_ckpt_every_k=ckpt_cfg.memory_ckpt_every_k,
            )
            try:
                shards = self._make_dataset_shards(world)
                group.setup(
                    restore.path if restore else None,
                    shards,
                    collective_group=f"train-{name}",
                )
                # Scope drain notices to this incarnation's hosts, then OR
                # in ground truth: a drain announced mid-setup (event
                # handled before this snapshot OR racing it) must still
                # trigger the grace-window save — never overwrite a
                # concurrently-set flag with a stale nodes() view.
                self._gang_nodes = set(group.gang_nodes)
                try:
                    if any(n.get("draining")
                           and n.get("node_id") in self._gang_nodes
                           for n in ray_tpu.nodes()):
                        with _drain_flag_lock:
                            self._drain_requested = True
                except Exception:
                    pass
                group.start_training(self.train_loop, self.train_loop_config)
                # Downsized? keep the shortfall visible as autoscaler
                # demand so the next restart can grow back to num_workers.
                self._set_backfill_demand(world)
                last_metrics, history_part = self._drive(group, manager)
                history.extend(history_part)
                error = None
                break
            except (WorkerCrashedError, ActorDiedError, ray_tpu.exceptions.RayTpuError) as e:
                failures += 1
                history_part = getattr(e, "_history", [])
                history.extend(history_part)
                # Fast gang recovery: the held disk-skipped round (already
                # on the driver's disk) first, then any NEWER in-memory
                # replicas pulled off the surviving workers BEFORE the gang
                # is torn down — resume loses seconds, not a checkpoint
                # interval.  This runs even when the failure is terminal:
                # Result.checkpoint must be the freshest restorable state
                # (a round must never vanish from both tiers just because
                # the retry budget ran out).
                # Best-effort like the replication that fed them: a broken
                # recovery tier (ENOSPC during register, a corrupt blob)
                # must degrade to the older disk checkpoint, not escape the
                # except clause and turn a retryable failure terminal.
                try:
                    self._flush_pending_skipped(manager)
                except Exception:
                    logger.exception("persisting the held checkpoint failed")
                try:
                    self._restore_from_memory_snapshots(group, manager)
                except Exception:
                    logger.exception("in-memory checkpoint recovery failed")
                if fail_cfg.max_failures >= 0 and failures > fail_cfg.max_failures:
                    error = TrainingFailedError(
                        f"training failed after {failures} failure(s): {e}"
                    )
                    break
                restore = manager.latest() or self.resume_from_checkpoint
            finally:
                group.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=manager.latest(),
            path=trial_dir,
            error=error,
            metrics_history=history,
        )

    # -------------------------------------------------------------- elastic

    def _clear_backfill_demand(self) -> None:
        pg, self._backfill_pg = self._backfill_pg, None
        if pg is not None:
            try:
                ray_tpu.remove_placement_group(pg)
            except Exception:
                pass

    def _set_backfill_demand(self, world: int) -> None:
        """Downsized gang: park a placement group for the MISSING capacity
        (num_workers - world bundles).  Pending, it is exactly the demand
        signal the autoscaler keys on; once satisfied it holds the arrived
        capacity until the next restart claims it for the upsize."""
        self._clear_backfill_demand()
        shortfall = self.scaling_config.num_workers - world
        if self.scaling_config.min_workers is None or shortfall <= 0:
            return
        self._backfill_pg = _quiet_demand_pg(
            self.scaling_config.worker_resources(), shortfall
        )

    def _resolve_world_size(self, settle: bool = False) -> int:
        """Largest feasible world size right now.  Non-elastic configs
        (min_workers=None) always get num_workers.  Elastic configs size the
        gang to the schedulable capacity within [min_workers, num_workers]:
        a preempted-but-unreplaced node shrinks the gang instead of stalling
        the run; a later restart on a backfilled cluster grows it back."""
        sc = self.scaling_config
        if sc.min_workers is None:
            return sc.num_workers
        # The previous incarnation's backfill reservation (if satisfied)
        # holds capacity that belongs to THIS measurement: release first.
        self._clear_backfill_demand()
        if settle:
            # Give the control plane a beat to notice the dead/drained node
            # (and release the dead gang's reservations) so capacity isn't
            # computed against a stale view.
            time.sleep(1.0)
        res = sc.worker_resources()
        key = "TPU" if sc.use_tpu else "CPU"
        per = res.get(key) or 1.0
        floor = max(1, min(sc.min_workers, sc.num_workers))

        def feasible_now() -> int:
            # AVAILABLE capacity, not totals: co-tenant workloads (serve
            # replicas, other jobs) must not be double-counted into the
            # gang — an oversized gang would park unplaceable actors.
            # Whole worker slots PER NODE, not a cross-node sum: three
            # nodes with 1 free CPU each cannot host one 2-CPU worker,
            # and an unplaceable gang would hang setup forever.
            slots = 0
            try:
                for n in ray_tpu.nodes():
                    if n.get("alive") and not n.get("draining"):
                        avail = (n.get("available") or {}).get(key, 0.0)
                        slots += int(avail // per)
            except Exception:
                pass
            return min(slots, sc.num_workers)

        deadline = time.monotonic() + sc.elastic_wait_s
        demand_pg = None

        def release_demand_pg():
            nonlocal demand_pg
            if demand_pg:
                try:
                    ray_tpu.remove_placement_group(demand_pg)
                except Exception:
                    pass
            demand_pg = None

        try:
            while True:
                # A demand PG that got SATISFIED holds real reservations —
                # release it BEFORE measuring, or its own bundles would be
                # subtracted from availability and the gang would re-form
                # undersized on a fully backfilled cluster.
                if demand_pg and demand_pg.ready(timeout=0.05):
                    release_demand_pg()
                feasible = feasible_now()
                if feasible >= sc.num_workers:
                    return feasible
                if feasible >= floor:
                    # Mid-range reading: the dead gang's releases may still
                    # be landing — confirm with a second poll and take the
                    # larger view before committing to a downsize.  Release
                    # the demand PG first: if it got satisfied in the gap
                    # after the ready() check above, its reservation would
                    # depress both readings and lock in an undersized gang.
                    release_demand_pg()
                    time.sleep(0.5)
                    return max(feasible, feasible_now())
                if time.monotonic() >= deadline:
                    raise TrainingFailedError(
                        f"elastic restart: only {feasible} worker slot(s) "
                        f"of {key!r} capacity available after "
                        f"{sc.elastic_wait_s}s; min_workers={floor}"
                    )
                if demand_pg is None:
                    # Make the wait visible as scheduler demand: a pending
                    # placement group is what the autoscaler keys on —
                    # without it a cold cluster would never backfill for us.
                    demand_pg = _quiet_demand_pg(res, floor) or False
                time.sleep(0.5)
        finally:
            release_demand_pg()

    def _restore_from_memory_snapshots(self, group: WorkerGroup,
                                       manager: CheckpointManager) -> None:
        """Materialize the freshest complete in-memory checkpoint set (if it
        beats the last disk write this incarnation) into the manager, so the
        normal latest()-restore path picks it up."""
        try:
            got = group.collect_memory_snapshots()
        except Exception:
            return
        if not got:
            return
        step, blobs = got
        if step <= self._last_disk_ckpt_step:
            return  # disk already has this round (e.g. a drain save landed)
        from .checkpoint import unpack_directory

        rank_dirs: List[str] = []
        for rank, blob in sorted(blobs.items()):
            d = tempfile.mkdtemp(prefix=f"rt_mem_ckpt_r{rank}_")
            unpack_directory(blob, d)
            rank_dirs.append(d)
        merged = self._merge_checkpoints(rank_dirs)
        persisted = manager.register(
            Checkpoint(merged),
            {"step": step, "memory_checkpoint": True},
        )
        # Durable marker: lets operators (and tests) see that this restore
        # point came from the in-memory replicas, not a periodic disk save.
        try:
            persisted.update_metadata(
                {"memory_checkpoint": True, "session_step": step}
            )
        except Exception:
            pass
        shutil.rmtree(merged, ignore_errors=True)
        for d in rank_dirs:
            shutil.rmtree(d, ignore_errors=True)

    # ---------------------------------------------------------------- drive

    def _drive(self, group: WorkerGroup, manager: CheckpointManager):
        """Poll report rounds until every worker finishes
        (reference: backend_executor.get_next_results:578).  Any failure
        carries the rounds processed so far (``e._history``) so an elastic
        restart doesn't lose the pre-failure metrics history."""
        history: List[dict] = []
        try:
            return self._drive_inner(group, manager, history)
        except BaseException as e:  # noqa: BLE001 — annotated and re-raised
            if not getattr(e, "_history", None):
                e._history = history
            raise

    def _drive_inner(self, group: WorkerGroup, manager: CheckpointManager,
                     history: List[dict]):
        last_metrics: Dict[str, Any] = {}
        done = [False] * group.num_workers
        while not all(done):
            active = [r for r in range(group.num_workers) if not done[r]]
            results = group.poll_all(active)
            reports = []
            for r in results:
                if r is None:
                    raise TrainingFailedError("worker poll timed out")
                if r.get("done"):
                    done[r["rank"]] = True
                    if r.get("error"):
                        err = TrainingFailedError(
                            f"rank {r['rank']} failed:\n{r['error']}"
                        )
                        err._history = history
                        raise err
                else:
                    reports.append(r)
            if reports:
                rank0 = next((r for r in reports if r["rank"] == 0), reports[0])
                metrics = rank0["metrics"]
                ckpt_dirs = [r["checkpoint_dir"] for r in reports
                             if r.get("checkpoint_dir")]
                if ckpt_dirs:
                    self._ckpt_rounds += 1
                    # Disk cadence: persist every K-th checkpoint round;
                    # drain saves (announced preemption) always persist.
                    # A round may ONLY skip disk when every reporting rank
                    # confirmed an in-memory replica for it — a checkpoint
                    # must never vanish from both tiers (e.g. single-worker
                    # gangs or replication disabled/mis-cadenced).
                    drain_round = any(r.get("drain") for r in reports)
                    replicated = all(
                        r.get("memory_replicated")
                        for r in reports if r.get("checkpoint_dir")
                    )
                    merged = self._merge_checkpoints(ckpt_dirs)
                    if (drain_round or not replicated
                            or self._ckpt_rounds % self._disk_every_k == 0):
                        self._last_disk_ckpt_step = rank0.get("step", 0)
                        manager.register(Checkpoint(merged), metrics)
                        shutil.rmtree(merged, ignore_errors=True)
                        self._drop_pending_skipped()
                    else:
                        # Skipped round: hold the newest merged copy on the
                        # DRIVER's disk until a newer round persists — the
                        # run's final checkpoint must never exist only in
                        # replicas that die with the gang at shutdown.
                        self._drop_pending_skipped()
                        self._pending_skipped = (
                            rank0.get("step", 0), merged, metrics
                        )
                    for d in ckpt_dirs:
                        shutil.rmtree(d, ignore_errors=True)
                last_metrics = metrics
                history.append(metrics)
                if self._report_callback is not None:
                    self._report_callback(metrics)
                # Relay a pending preemption notice on THIS round's acks:
                # every rank sees should_checkpoint() at the same boundary.
                group.ack_all([r["rank"] for r in reports],
                              should_checkpoint=self._consume_drain_notice())
        # Clean finish: if the run's newest checkpoint round was a disk-
        # skipped one, its in-memory replicas are about to die with the
        # gang — persist the held driver-side copy now.
        self._flush_pending_skipped(manager)
        return last_metrics, history

    def _drop_pending_skipped(self) -> None:
        pending, self._pending_skipped = self._pending_skipped, None
        if pending is not None:
            shutil.rmtree(pending[1], ignore_errors=True)

    def _flush_pending_skipped(self, manager: CheckpointManager) -> None:
        """Persist the newest disk-skipped checkpoint round (if any) —
        called when its in-memory replicas are about to become unreachable
        (clean finish, or a failure before collection)."""
        pending, self._pending_skipped = self._pending_skipped, None
        if pending is None:
            return
        step, merged, metrics = pending
        if step > self._last_disk_ckpt_step:
            self._last_disk_ckpt_step = step
            persisted = manager.register(Checkpoint(merged), metrics)
            try:
                persisted.update_metadata(
                    {"held_checkpoint": True, "session_step": step}
                )
            except Exception:
                pass
        shutil.rmtree(merged, ignore_errors=True)

    @staticmethod
    def _merge_checkpoints(dirs: List[str]) -> str:
        """Merge per-rank checkpoint dirs (rank files must be distinct or
        identical; rank 0 wins collisions by being copied last)."""
        merged = tempfile.mkdtemp(prefix="rt_merged_ckpt_")
        for d in sorted(dirs, reverse=True):
            shutil.copytree(d, merged, dirs_exist_ok=True)
        return merged

    def _make_dataset_shards(
        self, num_workers: Optional[int] = None
    ) -> Optional[List[Dict[str, Any]]]:
        if not self.datasets:
            return None
        n = num_workers or self.scaling_config.num_workers
        per_worker: List[Dict[str, Any]] = [dict() for _ in range(n)]
        for dname, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                shards = ds.streaming_split(n)
            elif isinstance(ds, (list, tuple)):
                shards = [list(ds[i::n]) for i in range(n)]
            else:
                shards = [ds] * n  # replicated (caller shards inside loop)
            for i in range(n):
                per_worker[i][dname] = shards[i]
        return per_worker


class JaxTrainer(DataParallelTrainer):
    """Alias emphasizing the JAX-native path (the reference's TorchTrainer
    analog — train/torch/torch_trainer.py:11)."""
