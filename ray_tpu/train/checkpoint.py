"""Checkpoints: directory handles + top-K retention.

Role-equivalent to the reference's train/_checkpoint.py:56 (Checkpoint as a
directory on a filesystem) and train/_internal/checkpoint_manager.py (top-K
by score).  Storage is a filesystem path (shared FS or local); model-state
serialization itself is the caller's business — `save_pytree`/`load_pytree`
helpers cover the common JAX case (device→host transfer + pickle-5 with
out-of-band-capable numpy arrays; arbitrary pytree structures round-trip).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple


class Checkpoint:
    """Handle to a checkpoint directory (reference: train/_checkpoint.py)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        meta_path = os.path.join(self.path, ".metadata.json")
        existing = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                existing = json.load(f)
        existing.update(metadata)
        with open(meta_path, "w") as f:
            json.dump(existing, f)

    def get_metadata(self) -> Dict[str, Any]:
        meta_path = os.path.join(self.path, ".metadata.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def save_pytree(tree: Any, directory: str, name: str = "state") -> None:
    """Save a JAX pytree into a checkpoint directory."""
    os.makedirs(directory, exist_ok=True)
    import jax
    import numpy as np

    host_tree = jax.tree.map(lambda x: np.asarray(x), tree)
    with open(os.path.join(directory, f"{name}.pkl"), "wb") as f:
        pickle.dump(host_tree, f, protocol=5)


def load_pytree(directory: str, name: str = "state") -> Any:
    with open(os.path.join(directory, f"{name}.pkl"), "rb") as f:
        return pickle.load(f)


def pack_directory(directory: str) -> bytes:
    """Flatten a checkpoint directory into one blob ({relpath: bytes},
    pickle-5) — the wire/object-store form of an in-memory checkpoint
    replica (see CheckpointConfig.memory_ckpt_every_k)."""
    files: Dict[str, bytes] = {}
    for root, _, names in os.walk(directory):
        for name in names:
            path = os.path.join(root, name)
            with open(path, "rb") as f:
                files[os.path.relpath(path, directory)] = f.read()
    return pickle.dumps(files, protocol=5)


def unpack_directory(blob: bytes, directory: str) -> str:
    """Materialize a pack_directory blob back into a directory."""
    files = pickle.loads(blob)
    for rel, data in files.items():
        path = os.path.join(directory, rel)
        os.makedirs(os.path.dirname(path) or directory, exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
    os.makedirs(directory, exist_ok=True)  # empty checkpoints stay loadable
    return directory


class AsyncCheckpointWriter:
    """Overlapped checkpoint saves: ``save()`` snapshots the pytree to host
    memory synchronously (cheap: the D2H DMA is kicked with
    ``copy_to_host_async`` first, so the transfers run in parallel and the
    blocking part is just their completion), then serialization and disk IO
    run on a background thread while the train loop keeps stepping.

    The synchronous host snapshot is REQUIRED for correctness, not an
    implementation detail: the default train step donates the state
    (models/train_state.py donate_state=True), so the device buffers are
    deleted by the very next step — a background thread reading live
    jax.Arrays would crash.  What overlaps is the expensive part (pickle +
    disk/remote IO — the reference gets the same overlap from Tune's
    threaded checkpoint upload, train/_internal/storage.py; SURVEY §7
    lists async checkpointing as an MFU requirement).

    One save is in flight at a time: a new ``save`` waits for the previous
    write to land (bounded memory, ordered checkpoints).  The writer
    thread is non-daemon, so a process that exits right after ``save``
    still finishes the write.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, tree: Any, directory: str, name: str = "state") -> None:
        """Snapshot ``tree`` to host and start the async write.  Blocks
        only for the D2H copy (and any unfinished previous save)."""
        import jax
        import numpy as np

        self.wait()  # one in flight; surfaces prior errors
        # Kick every transfer first so they overlap each other...
        jax.tree.map(
            lambda x: x.copy_to_host_async()
            if hasattr(x, "copy_to_host_async") else None,
            tree,
        )
        # ...then complete them into host arrays.  After this line the
        # snapshot is independent of device state (donation-safe).
        host_tree = jax.tree.map(lambda x: np.asarray(x), tree)

        def write():
            tmp = directory + f".tmp-{os.getpid()}"
            try:
                os.makedirs(tmp, exist_ok=True)
                with open(os.path.join(tmp, f"{name}.pkl"), "wb") as f:
                    pickle.dump(host_tree, f, protocol=5)
                # Publish without a window where NO checkpoint exists:
                # move the previous good dir aside (unique name), rename
                # the new one in, then drop the old.  A crash mid-sequence
                # leaves dest or a dest.old-* loadable — `recover` restores
                # the newest one.
                old = None
                if os.path.isdir(directory):
                    old = f"{directory}.old-{uuid.uuid4().hex[:8]}"
                    os.rename(directory, old)
                os.rename(tmp, directory)
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
                # Sweep stale .old-* left by crashes of earlier publishes.
                parent = os.path.dirname(directory) or "."
                base = os.path.basename(directory)
                for entry in os.listdir(parent):
                    if entry.startswith(base + ".old-"):
                        shutil.rmtree(os.path.join(parent, entry),
                                      ignore_errors=True)
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                self._error = e
                shutil.rmtree(tmp, ignore_errors=True)  # never reuse stale tmp

        with self._lock:
            self._pending = threading.Thread(
                target=write, name="async-ckpt"
            )
            self._pending.start()

    @staticmethod
    def recover(directory: str) -> Optional[str]:
        """Crash recovery: if ``directory`` is missing but a publish left a
        ``.old-*`` sibling, restore the newest one and return the usable
        path (or None when nothing is recoverable)."""
        if os.path.isdir(directory):
            return directory
        parent = os.path.dirname(directory) or "."
        base = os.path.basename(directory)
        try:
            candidates = sorted(
                (e for e in os.listdir(parent)
                 if e.startswith(base + ".old-")),
                key=lambda e: os.path.getmtime(os.path.join(parent, e)),
            )
        except OSError:
            return None
        if not candidates:
            return None
        os.rename(os.path.join(parent, candidates[-1]), directory)
        return directory

    def wait(self) -> None:
        """Block until the in-flight save (if any) is durable; re-raises a
        failed write here rather than losing it."""
        with self._lock:
            t = self._pending
        if t is not None:
            t.join()
            with self._lock:
                self._pending = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


class CheckpointManager:
    """Keeps the top-K checkpoints by score under a storage directory
    (reference: train/_internal/checkpoint_manager.py)."""

    def __init__(
        self,
        storage_dir: str,
        num_to_keep: Optional[int] = None,
        score_attribute: Optional[str] = None,
        score_order: str = "max",
    ):
        self.storage_dir = storage_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        # [(score, index, Checkpoint, metrics)]
        self.checkpoints: List[Tuple[float, int, Checkpoint, dict]] = []
        self._index = 0
        os.makedirs(storage_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        """Persist a (worker-local) checkpoint into storage and apply the
        retention policy.  Returns the persisted handle."""
        self._index += 1
        dest = os.path.join(self.storage_dir, f"checkpoint_{self._index:06d}")
        if os.path.abspath(checkpoint.path) != dest:
            shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        persisted = Checkpoint(dest)
        if self.score_attribute and self.score_attribute in metrics:
            score = float(metrics[self.score_attribute])
        else:
            score = float(self._index)  # recency
        if self.score_order == "min":
            score = -score
        self.checkpoints.append((score, self._index, persisted, dict(metrics)))
        self._apply_retention()
        return persisted

    def _apply_retention(self):
        if self.num_to_keep is None or len(self.checkpoints) <= self.num_to_keep:
            return
        self.checkpoints.sort(key=lambda t: (t[0], t[1]))
        while len(self.checkpoints) > self.num_to_keep:
            _, _, ckpt, _ = self.checkpoints.pop(0)  # worst first
            shutil.rmtree(ckpt.path, ignore_errors=True)

    def latest(self) -> Optional[Checkpoint]:
        if not self.checkpoints:
            return None
        return max(self.checkpoints, key=lambda t: t[1])[2]

    def best(self) -> Optional[Checkpoint]:
        if not self.checkpoints:
            return None
        return max(self.checkpoints, key=lambda t: (t[0], t[1]))[2]
