"""Checkpoints: directory handles + top-K retention.

Role-equivalent to the reference's train/_checkpoint.py:56 (Checkpoint as a
directory on a filesystem) and train/_internal/checkpoint_manager.py (top-K
by score).  Storage is a filesystem path (shared FS or local); model-state
serialization itself is the caller's business — `save_pytree`/`load_pytree`
helpers cover the common JAX case (device→host transfer + pickle-5 with
out-of-band-capable numpy arrays; arbitrary pytree structures round-trip).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple


class Checkpoint:
    """Handle to a checkpoint directory (reference: train/_checkpoint.py)."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def as_directory(self) -> str:
        return self.path

    def to_directory(self, dest: Optional[str] = None) -> str:
        dest = dest or tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        meta_path = os.path.join(self.path, ".metadata.json")
        existing = {}
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                existing = json.load(f)
        existing.update(metadata)
        with open(meta_path, "w") as f:
            json.dump(existing, f)

    def get_metadata(self) -> Dict[str, Any]:
        meta_path = os.path.join(self.path, ".metadata.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


def save_pytree(tree: Any, directory: str, name: str = "state") -> None:
    """Save a JAX pytree into a checkpoint directory."""
    os.makedirs(directory, exist_ok=True)
    import jax
    import numpy as np

    host_tree = jax.tree.map(lambda x: np.asarray(x), tree)
    with open(os.path.join(directory, f"{name}.pkl"), "wb") as f:
        pickle.dump(host_tree, f, protocol=5)


def load_pytree(directory: str, name: str = "state") -> Any:
    with open(os.path.join(directory, f"{name}.pkl"), "rb") as f:
        return pickle.load(f)


class AsyncCheckpointWriter:
    """Non-blocking checkpoint saves: the device→host DMA starts
    immediately (`copy_to_host_async`), serialization and disk IO run on a
    background thread, and the train loop keeps stepping.

    This is the async-checkpointing requirement from the scaling plan
    (SURVEY §7: MFU at scale needs checkpoint writes overlapped with
    compute; the reference reaches the same overlap through Tune's
    threaded checkpoint upload, train/_internal/storage.py).  JAX arrays
    are immutable, so holding the snapshot's references keeps the old
    params alive (HBM cost of one extra copy) while the next steps write
    new buffers — no torment about torn state.

    One save is in flight at a time: a new `save` waits for the previous
    write to land (bounded memory, ordered checkpoints).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, tree: Any, directory: str, name: str = "state") -> None:
        """Start an async save of ``tree`` into ``directory``.  Blocks only
        if the previous save hasn't finished."""
        import jax

        self.wait()  # one in flight; surfaces prior errors
        # Kick the D2H transfers now so they overlap the next train step.
        jax.tree.map(
            lambda x: x.copy_to_host_async()
            if hasattr(x, "copy_to_host_async") else None,
            tree,
        )

        def write():
            tmp = directory + f".tmp-{os.getpid()}"
            old = directory + ".old"
            try:
                save_pytree(tree, tmp, name)  # np.asarray completes the DMA
                # Publish without a window where NO checkpoint exists: the
                # previous good dir moves aside first, the new one renames
                # in, then the old is dropped.  A crash mid-sequence leaves
                # either dest or dest.old loadable (never neither).
                shutil.rmtree(old, ignore_errors=True)
                if os.path.isdir(directory):
                    os.rename(directory, old)
                os.rename(tmp, directory)
                shutil.rmtree(old, ignore_errors=True)
            except BaseException as e:  # noqa: BLE001 — surfaced on wait()
                self._error = e
                shutil.rmtree(tmp, ignore_errors=True)  # never reuse stale tmp

        with self._lock:
            self._pending = threading.Thread(
                target=write, name="async-ckpt", daemon=True
            )
            self._pending.start()

    def wait(self) -> None:
        """Block until the in-flight save (if any) is durable; re-raises a
        failed write here rather than losing it."""
        with self._lock:
            t = self._pending
        if t is not None:
            t.join()
            with self._lock:
                self._pending = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


class CheckpointManager:
    """Keeps the top-K checkpoints by score under a storage directory
    (reference: train/_internal/checkpoint_manager.py)."""

    def __init__(
        self,
        storage_dir: str,
        num_to_keep: Optional[int] = None,
        score_attribute: Optional[str] = None,
        score_order: str = "max",
    ):
        self.storage_dir = storage_dir
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        # [(score, index, Checkpoint, metrics)]
        self.checkpoints: List[Tuple[float, int, Checkpoint, dict]] = []
        self._index = 0
        os.makedirs(storage_dir, exist_ok=True)

    def register(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        """Persist a (worker-local) checkpoint into storage and apply the
        retention policy.  Returns the persisted handle."""
        self._index += 1
        dest = os.path.join(self.storage_dir, f"checkpoint_{self._index:06d}")
        if os.path.abspath(checkpoint.path) != dest:
            shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        persisted = Checkpoint(dest)
        if self.score_attribute and self.score_attribute in metrics:
            score = float(metrics[self.score_attribute])
        else:
            score = float(self._index)  # recency
        if self.score_order == "min":
            score = -score
        self.checkpoints.append((score, self._index, persisted, dict(metrics)))
        self._apply_retention()
        return persisted

    def _apply_retention(self):
        if self.num_to_keep is None or len(self.checkpoints) <= self.num_to_keep:
            return
        self.checkpoints.sort(key=lambda t: (t[0], t[1]))
        while len(self.checkpoints) > self.num_to_keep:
            _, _, ckpt, _ = self.checkpoints.pop(0)  # worst first
            shutil.rmtree(ckpt.path, ignore_errors=True)

    def latest(self) -> Optional[Checkpoint]:
        if not self.checkpoints:
            return None
        return max(self.checkpoints, key=lambda t: t[1])[2]

    def best(self) -> Optional[Checkpoint]:
        if not self.checkpoints:
            return None
        return max(self.checkpoints, key=lambda t: (t[0], t[1]))[2]
