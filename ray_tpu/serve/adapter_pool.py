"""Bounded device-resident LoRA adapter pool for the inference engine.

Role-equivalent to multi-LoRA serving in the Ray Serve LLM stack
(reference: Serve's LLM deployments multiplex many fine-tuned variants
over shared base weights), built like the KV :class:`PageAllocator`: a
host-side free list over fixed device slots.  The device arrays are ONE
stacked tensor per LoRA matrix (``models/paged.init_adapter_pool``), so
which adapter a batch slot uses is per-step DATA — loading, evicting, or
remixing adapters never recompiles the decode program.

Slots are pinned while any in-flight sequence decodes with them; only
unpinned residents are LRU-evictable.  Adapter weights page in through
the object plane (an ``ObjectRef`` registered once cluster-wide) or from
host arrays; eviction is free — the slot is simply overwritten by the
next load, and index ``max_adapters`` is the permanent zero adapter for
base-model traffic.

Not thread-safe by design: the engine's loop thread owns the pool the
same way it owns the KV pools (acquire/release only happen between
decode steps).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Optional


class AdapterNotFoundError(KeyError):
    """Request named an adapter that was never registered."""


class AdapterPool:
    """Fixed number of device-resident adapter slots + host registry of
    every known adapter's weights (packed arrays, a lazy builder, or an
    object-plane ref)."""

    def __init__(self, model_config, max_adapters: int = 4,
                 rank: int = 8):
        from ..devtools import jitguard
        from ..models.paged import init_adapter_pool

        # A fresh pool may carry a new rank/slot-count shape: stand the
        # adapter_load program's armed baseline down (recompile sentinel)
        # so its cold trace isn't mistaken for a hot-path recompile.
        jitguard.register_program("adapter_load")
        self.model_config = model_config
        self.max_adapters = max_adapters
        self.rank = rank
        self.arrays = init_adapter_pool(model_config, max_adapters, rank)
        self._free: List[int] = list(range(max_adapters))
        self._slots: Dict[str, int] = {}       # resident name -> slot
        self._pins: Dict[str, int] = {}        # resident name -> pin count
        self._lru: "OrderedDict[str, None]" = OrderedDict()  # recency
        self._sources: Dict[str, Any] = {}     # name -> weight source
        self._pending: set = set()             # reserved, weights not loaded
        self.evictions = 0
        self.loads = 0

    # ------------------------------------------------------------ registry

    @property
    def zero_slot(self) -> int:
        """Slot index decoding base-model requests (all-zero delta)."""
        return self.max_adapters

    def register(self, name: str, source: Any) -> bool:
        """Make ``name`` loadable.  ``source`` is packed arrays (see
        ``pack_lora``), a ``lora_init``-style pytree, an object-plane ref
        holding either, or a zero-arg callable returning either.
        Re-registering drops any resident copy (the weights changed —
        the caller must also drop derived state like cached prefixes).
        Returns True when a resident copy was dropped."""
        self._sources[name] = source
        if name in self._slots:
            if self._pins.get(name, 0):
                raise RuntimeError(
                    f"adapter {name!r} re-registered while pinned by "
                    "in-flight sequences")
            self._free.append(self._slots.pop(name))
            self._pins.pop(name, None)
            self._lru.pop(name, None)
            self._pending.discard(name)
            return True
        return False

    def has(self, name: str) -> bool:
        return name in self._sources

    def resident(self, name: str) -> bool:
        return name in self._slots

    def names(self) -> List[str]:
        return list(self._sources)

    # ------------------------------------------------------- acquire/release

    def can_acquire(self, name: Optional[str]) -> bool:
        """Admission-time check (no device work): would ``acquire``
        succeed right now?  True for base-model requests, residents,
        free slots, and evictable (unpinned) residents."""
        if name is None or name in self._slots or self._free:
            return name is None or name in self._sources
        if name not in self._sources:
            return False
        return any(self._pins.get(n, 0) == 0 for n in self._slots)

    def reserve(self, name: Optional[str]) -> int:
        """Pin ``name`` into a slot WITHOUT loading weights (host-only —
        safe under the engine lock).  Admission reserves so that requests
        admitted in the same round see each other's pins; the prefill
        path loads via :meth:`ensure_loaded` before the slot is read."""
        if name is None:
            return self.zero_slot
        if name not in self._sources:
            raise AdapterNotFoundError(name)
        slot = self._slots.get(name)
        if slot is None:
            slot = self._take_slot()
            self._slots[name] = slot
            self._pending.add(name)
        self._pins[name] = self._pins.get(name, 0) + 1
        self._lru[name] = None
        self._lru.move_to_end(name)
        return slot

    def ensure_loaded(self, name: Optional[str]) -> None:
        """Materialize a reserved adapter's weights into its slot (device
        work, loop thread only).  No-op for loaded residents."""
        if name is not None and name in self._pending:
            self._load(name, self._slots[name])
            self._pending.discard(name)

    def acquire(self, name: Optional[str]) -> int:
        """Pin ``name`` into a slot (loading/evicting on demand — device
        work, loop thread only) and return the slot index."""
        slot = self.reserve(name)
        self.ensure_loaded(name)
        return slot

    def release(self, name: Optional[str]) -> None:
        if name is None:
            return
        n = self._pins.get(name, 0)
        if n <= 0:
            raise AssertionError(f"release of unpinned adapter {name!r}")
        self._pins[name] = n - 1

    def reset(self) -> None:
        """Drop all residency and pins and rebuild the device arrays
        (after a failed donated call may have invalidated them).  The
        registry survives — adapters reload on next acquire."""
        from ..models.paged import init_adapter_pool

        self.arrays = init_adapter_pool(
            self.model_config, self.max_adapters, self.rank)
        self._free = list(range(self.max_adapters))
        self._slots.clear()
        self._pins.clear()
        self._lru.clear()
        self._pending.clear()

    def warmup_compile(self) -> None:
        """Trace the ``adapter_load`` program before the recompile
        sentinel arms (engine ``warmup()``): a zero payload written into
        the permanent zero slot is a value no-op, but it compiles the
        load path so the first REAL adapter load after warmup is an
        execution, not a post-warmup trace.  Loop thread only (device
        work, donates the arrays like any load)."""
        import jax.numpy as jnp

        from ..models.paged import adapter_load

        packed = {name: jnp.zeros_like(arr[0])
                  for name, arr in self.arrays.items()}
        self.arrays = adapter_load(
            self.arrays, jnp.asarray(self.zero_slot, jnp.int32), packed)

    # -------------------------------------------------------------- internal

    def _take_slot(self) -> int:
        if self._free:
            return self._free.pop()
        for victim in self._lru:  # oldest first
            if self._pins.get(victim, 0) == 0:
                self.evictions += 1
                self._lru.pop(victim)
                self._pins.pop(victim, None)
                self._pending.discard(victim)
                return self._slots.pop(victim)
        raise RuntimeError(
            f"all {self.max_adapters} adapter slots pinned by in-flight "
            "sequences — admission should have checked can_acquire()")

    def _load(self, name: str, slot: int) -> None:
        import jax.numpy as jnp

        from ..models.paged import adapter_load

        packed = self._materialize(self._sources[name])
        self.arrays = adapter_load(
            self.arrays, jnp.asarray(slot, jnp.int32), packed)
        self._slots[name] = slot
        self.loads += 1

    def _materialize(self, source: Any):
        from ..core.object_ref import ObjectRef
        from ..models.paged import pack_lora

        if isinstance(source, ObjectRef):
            from ..core.api import get

            source = get(source)
        if callable(source):
            source = source()
        if isinstance(source, dict) and "layers" in source:
            source = pack_lora(self.model_config, source)
        return source

    @property
    def pinned_count(self) -> int:
        """Adapters currently pinned by in-flight sequences (cheap: read
        on the engine's step-record path every decode step)."""
        return sum(1 for c in self._pins.values() if c)

    def stats(self) -> Dict[str, Any]:
        return {
            "registered": len(self._sources),
            "resident": sorted(self._slots),
            "pinned": {n: c for n, c in self._pins.items() if c},
            "free_slots": len(self._free),
            "evictions": self.evictions,
            "loads": self.loads,
        }
