"""Public Serve API: @deployment, run, handles, HTTP ingress.

Role-equivalent to the reference's serve.api
(reference: serve/api.py:510 serve.run -> controller deploy; deployment
decorator serve/deployment.py; stdlib-http ingress plays the HTTPProxy role,
reference: serve/_private/proxy.py:766).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu
from ..train.worker_group import _dumps_by_value
from .controller import CONTROLLER_NAME, get_or_create_controller
from .handle import DeploymentHandle


class Application:
    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


class Deployment:
    def __init__(self, cls_or_fn: Callable, name: str,
                 num_replicas: int = 1,
                 max_concurrent_queries: int = 8,
                 ray_actor_options: Optional[dict] = None,
                 autoscaling_config: Optional[dict] = None):
        self._callable = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.max_concurrent_queries = max_concurrent_queries
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config

    def options(self, **overrides) -> "Deployment":
        fields = {
            "name": self.name,
            "num_replicas": self.num_replicas,
            "max_concurrent_queries": self.max_concurrent_queries,
            "ray_actor_options": self.ray_actor_options,
            "autoscaling_config": self.autoscaling_config,
        }
        fields.update(overrides)
        return Deployment(self._callable, **fields)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def to_spec(self, app: Application) -> dict:
        res = {}
        opts = self.ray_actor_options
        if opts.get("num_cpus") is not None:
            res["CPU"] = opts["num_cpus"]
        if opts.get("num_tpus"):
            res["TPU"] = opts["num_tpus"]
        spec = {
            "cls_blob": _dumps_by_value(self._callable),
            "init_args_blob": cloudpickle.dumps(
                (app.init_args, app.init_kwargs)
            ),
            "num_replicas": self.num_replicas,
            "max_concurrent": self.max_concurrent_queries,
            "resources": res,
        }
        if self.autoscaling_config:
            ac = dict(self.autoscaling_config)
            ac.setdefault("min_replicas", 1)
            ac.setdefault("max_replicas", max(ac["min_replicas"], 4))
            spec["autoscaling"] = ac
        return spec


def deployment(_cls=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               max_concurrent_queries: int = 8,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None):
    """@serve.deployment decorator (reference: serve/deployment.py)."""

    def deco(cls_or_fn):
        return Deployment(
            cls_or_fn,
            name or getattr(cls_or_fn, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=ray_actor_options,
            autoscaling_config=autoscaling_config,
        )

    if _cls is not None:
        return deco(_cls)
    return deco


def run(app: Application, *, name: Optional[str] = None,
        wait_ready: bool = True, timeout: float = 120.0) -> DeploymentHandle:
    """Deploy an application and return its handle (reference:
    serve/api.py:510 serve.run)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    dep = app.deployment
    dep_name = name or dep.name
    controller = get_or_create_controller()
    ray_tpu.get(controller.deploy.remote(dep_name, dep.to_spec(app)),
                timeout=timeout)
    if wait_ready:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if ray_tpu.get(controller.ready.remote(dep_name), timeout=30):
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(f"deployment {dep_name!r} not ready")
    return DeploymentHandle(dep_name)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status() -> Dict[str, Any]:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=30)


def delete(name: str):
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete.remote(name), timeout=30)


def shutdown():
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:
        pass


# ------------------------------------------------------------- HTTP ingress


class _HttpProxy:
    """Minimal stdlib HTTP ingress: POST /<deployment> with a JSON body
    calls the deployment and returns the JSON result (the HTTPProxy role,
    reference: serve/_private/proxy.py:766 routed by LongestPrefixRouter)."""

    def __init__(self, host: str, port: int):
        import http.server

        handles: Dict[str, DeploymentHandle] = {}

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 — stdlib naming
                name = self.path.strip("/").split("/")[0]
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                    payload = json.loads(body) if body else None
                    h = handles.get(name)
                    if h is None:
                        h = handles[name] = DeploymentHandle(name)
                    if isinstance(payload, dict):
                        resp = h.remote(**payload).result()
                    elif payload is None:
                        resp = h.remote().result()
                    else:
                        resp = h.remote(payload).result()
                    out = json.dumps(resp).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001 — surfaces as a 500
                    out = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def log_message(self, *a):  # quiet
                pass

        self.server = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True,
                         name="serve-http").start()

    def close(self):
        self.server.shutdown()


_proxy: Optional[_HttpProxy] = None


def start_http(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the HTTP ingress; returns the bound port."""
    global _proxy
    if _proxy is None:
        _proxy = _HttpProxy(host, port)
    return _proxy.port


def stop_http():
    global _proxy
    if _proxy is not None:
        _proxy.close()
        _proxy = None
