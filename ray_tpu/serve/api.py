"""Public Serve API: @deployment, run, handles, HTTP ingress.

Role-equivalent to the reference's serve.api
(reference: serve/api.py:510 serve.run -> controller deploy; deployment
decorator serve/deployment.py; stdlib-http ingress plays the HTTPProxy role,
reference: serve/_private/proxy.py:766).
"""

from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, Optional

import cloudpickle

import ray_tpu
from ..train.worker_group import _dumps_by_value
from .controller import CONTROLLER_NAME, get_or_create_controller
from .handle import DeploymentHandle


class Application:
    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.init_args = args
        self.init_kwargs = kwargs


class Deployment:
    def __init__(self, cls_or_fn: Callable, name: str,
                 num_replicas: int = 1,
                 max_concurrent_queries: int = 8,
                 ray_actor_options: Optional[dict] = None,
                 autoscaling_config: Optional[dict] = None):
        self._callable = cls_or_fn
        self.name = name
        self.num_replicas = num_replicas
        self.max_concurrent_queries = max_concurrent_queries
        self.ray_actor_options = ray_actor_options or {}
        self.autoscaling_config = autoscaling_config

    def options(self, **overrides) -> "Deployment":
        fields = {
            "name": self.name,
            "num_replicas": self.num_replicas,
            "max_concurrent_queries": self.max_concurrent_queries,
            "ray_actor_options": self.ray_actor_options,
            "autoscaling_config": self.autoscaling_config,
        }
        fields.update(overrides)
        return Deployment(self._callable, **fields)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def to_spec(self, app: Application) -> dict:
        res = {}
        opts = self.ray_actor_options
        if opts.get("num_cpus") is not None:
            res["CPU"] = opts["num_cpus"]
        if opts.get("num_tpus"):
            res["TPU"] = opts["num_tpus"]
        spec = {
            "cls_blob": _dumps_by_value(self._callable),
            "init_args_blob": cloudpickle.dumps(
                (app.init_args, app.init_kwargs)
            ),
            "num_replicas": self.num_replicas,
            "max_concurrent": self.max_concurrent_queries,
            "resources": res,
        }
        if self.autoscaling_config:
            ac = dict(self.autoscaling_config)
            ac.setdefault("min_replicas", 1)
            ac.setdefault("max_replicas", max(ac["min_replicas"], 4))
            spec["autoscaling"] = ac
        return spec


def deployment(_cls=None, *, name: Optional[str] = None,
               num_replicas: int = 1,
               max_concurrent_queries: int = 8,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None):
    """@serve.deployment decorator (reference: serve/deployment.py)."""

    def deco(cls_or_fn):
        return Deployment(
            cls_or_fn,
            name or getattr(cls_or_fn, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            ray_actor_options=ray_actor_options,
            autoscaling_config=autoscaling_config,
        )

    if _cls is not None:
        return deco(_cls)
    return deco


def run(app: Application, *, name: Optional[str] = None,
        wait_ready: bool = True, timeout: float = 120.0) -> DeploymentHandle:
    """Deploy an application (and every application bound into its init
    args) and return the ingress handle (reference: serve/api.py:510
    serve.run; nested binds mirror the deployment-graph build at
    serve/_private/deployment_graph_build.py — each node becomes its own
    deployment and downstream nodes receive DeploymentHandles)."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    controller = get_or_create_controller()
    deployed: list = []
    # Diamond reuse: the same bound Application object deploys once; two
    # DIFFERENT binds of one class get suffixed names (reference:
    # deployment_graph_build.py disambiguates duplicate node names).
    seen: Dict[int, DeploymentHandle] = {}
    used_names: Dict[str, int] = {}

    def deploy_tree(a: Application, override_name: Optional[str] = None
                    ) -> DeploymentHandle:
        if id(a) in seen:
            return seen[id(a)]
        dep = a.deployment
        dep_name = override_name or dep.name
        if override_name is None:
            n = used_names.get(dep_name, 0)
            used_names[dep_name] = n + 1
            if n:
                dep_name = f"{dep_name}_{n + 1}"
        args = tuple(
            deploy_tree(x) if isinstance(x, Application) else x
            for x in a.init_args
        )
        kwargs = {
            k: deploy_tree(v) if isinstance(v, Application) else v
            for k, v in a.init_kwargs.items()
        }
        resolved = Application(dep, args, kwargs)
        ray_tpu.get(controller.deploy.remote(dep_name, dep.to_spec(resolved)),
                    timeout=timeout)
        deployed.append(dep_name)
        handle = DeploymentHandle(dep_name)
        seen[id(a)] = handle
        return handle

    handle = deploy_tree(app, override_name=name)
    if wait_ready:
        import time

        deadline = time.monotonic() + timeout
        for dep_name in deployed:
            while time.monotonic() < deadline:
                if ray_tpu.get(controller.ready.remote(dep_name), timeout=30):
                    break
                time.sleep(0.1)
            else:
                raise TimeoutError(f"deployment {dep_name!r} not ready")
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status() -> Dict[str, Any]:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=30)


def delete(name: str):
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete.remote(name), timeout=30)


def shutdown():
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:
        pass


# ------------------------------------------------------------- HTTP ingress


class _HttpProxy:
    """Minimal stdlib HTTP ingress: POST /<deployment> with a JSON body
    calls the deployment and returns the JSON result (the HTTPProxy role,
    reference: serve/_private/proxy.py:766 routed by LongestPrefixRouter)."""

    def __init__(self, host: str, port: int):
        import http.server

        handles: Dict[tuple, DeploymentHandle] = {}

        class Handler(http.server.BaseHTTPRequestHandler):
            def _stream_sse(self, gen_handle: DeploymentHandle, payload,
                            trace_id=None):
                """Server-sent events over a generator deployment
                (reference: proxy.py:537-598 — the HTTP proxy streams
                responses chunk-by-chunk as the replica produces them).
                One `data:` frame per yielded item, flushed immediately;
                buffering is one item in this thread, the rest in the
                object store."""
                if isinstance(payload, dict):
                    stream = gen_handle.remote(**payload)
                elif payload is None:
                    stream = gen_handle.remote()
                else:
                    stream = gen_handle.remote(payload)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                if trace_id:
                    # Request-tracing handshake: the client can feed this
                    # straight to `python -m ray_tpu trace <id>`.
                    self.send_header("X-RT-Trace-Id", trace_id)
                self.end_headers()
                completed = False
                try:
                    for item in stream:
                        self.wfile.write(
                            b"data: " + json.dumps(item).encode() + b"\n\n")
                        self.wfile.flush()
                    self.wfile.write(b"event: done\ndata: null\n\n")
                    self.wfile.flush()
                    completed = True
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client hung up: the finally cancels
                except Exception as e:  # noqa: BLE001 — headers are out;
                    # the error must travel IN the stream, not as a status.
                    try:
                        self.wfile.write(
                            b"event: error\ndata: "
                            + json.dumps(str(e)).encode() + b"\n\n")
                        self.wfile.flush()
                    except OSError:
                        pass
                finally:
                    if not completed:
                        # ANY non-complete exit (client hangup, write
                        # timeout, serialization error) cancels the
                        # replica-side generator so an engine-backed
                        # deployment stops decoding and frees its KV
                        # pages mid-flight.  Idempotent.
                        stream.cancel()

            def do_POST(self):  # noqa: N802 — stdlib naming
                from ray_tpu.util import tracing

                name = self.path.strip("/").split("/")[0]
                want_stream = "text/event-stream" in (
                    self.headers.get("Accept") or "")
                trace_id = None
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(n)
                    payload = json.loads(body) if body else None
                    # Multi-tenant ingress: X-RT-Tenant rides into the
                    # deployment as the ``tenant`` kwarg so engine-backed
                    # deployments (LLMServer) apply per-tenant admission
                    # and accounting.  A tenant already in the body wins —
                    # the header is the transport-level default.
                    tenant = (self.headers.get("X-RT-Tenant") or "").strip()
                    if tenant and isinstance(payload, dict):
                        payload.setdefault("tenant", tenant)
                    # Stream-mode handles are cached alongside unary ones:
                    # a fresh handle per request would pay a controller
                    # routing RPC and lose the p2c load counts.
                    key = (name, want_stream)
                    h = handles.get(key)
                    if h is None:
                        h = handles[key] = DeploymentHandle(
                            name, stream=want_stream)
                    # Per-request root span (sampling per the head's
                    # trace_sample_rate): the whole serve chain — handle,
                    # replica, engine — nests under it, so one trace id
                    # answers "where did this request's latency go".
                    # X-RT-Force-Trace: 1 is the per-call override.
                    force = (self.headers.get("X-RT-Force-Trace") or "") \
                        in ("1", "true")
                    with tracing.trace(f"ingress:{name}", force=force,
                                       proto="http",
                                       stream=want_stream) as tctx:
                        trace_id = tctx.get("trace_id")
                        if want_stream:
                            self._stream_sse(h, payload, trace_id)
                            return
                        if isinstance(payload, dict):
                            resp = h.remote(**payload).result()
                        elif payload is None:
                            resp = h.remote().result()
                        else:
                            resp = h.remote(payload).result()
                    out = json.dumps(resp).encode()
                    self.send_response(200)
                except Exception as e:  # noqa: BLE001 — surfaces as a 500
                    out = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                if trace_id:
                    self.send_header("X-RT-Trace-Id", trace_id)
                self.end_headers()
                self.wfile.write(out)

            def log_message(self, *a):  # quiet
                pass

        self.server = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True,
                         name="serve-http").start()

    def close(self):
        self.server.shutdown()


_proxy: Optional[_HttpProxy] = None


def start_http(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the HTTP ingress; returns the bound port."""
    global _proxy
    if _proxy is None:
        _proxy = _HttpProxy(host, port)
    return _proxy.port


def stop_http():
    global _proxy
    if _proxy is not None:
        _proxy.close()
        _proxy = None
