"""Model multiplexing: many models share one replica pool.

Role-equivalent to the reference's serve.multiplexed / get_multiplexed_model_id
(reference: serve/multiplex.py _ModelMultiplexWrapper — per-replica LRU of
loaded models, model-id-aware routing in the replica scheduler) — re-designed
for this framework: the loader decorator keeps an LRU on the replica, the
request's model id travels in request metadata and is exposed through a
contextvar, and the handle routes a given model id to a stable replica
(hash affinity) so repeated requests hit a warm cache.
"""

from __future__ import annotations

import contextvars
import functools
import inspect
from collections import OrderedDict
from typing import Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rt_serve_multiplexed_model_id", default=""
)


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (reference:
    serve/api.py get_multiplexed_model_id)."""
    return _current_model_id.get()


def pick_replica_for_model(model_id: str, replica_ids) -> int:
    """Rendezvous (highest-random-weight) hashing: return the INDEX into
    ``replica_ids`` of the replica that owns ``model_id``.

    Unlike ``hash(model_id) % n``, scaling from n to n+1 replicas only
    remaps ~1/(n+1) of the model ids — every other model keeps its warm
    replica-side LRU (reference: the replica scheduler's model-id
    affinity survives replica-set churn).  ``replica_ids`` must be the
    controller-issued STABLE ids, not list positions: positions shift on
    any membership change, stable ids only vanish with their replica."""
    import hashlib

    best, best_w = 0, b""
    for i, rid in enumerate(replica_ids):
        w = hashlib.md5(f"{model_id}:{rid}".encode()).digest()
        if w > best_w:
            best_w, best = w, i
    return best


def _set_model_id(model_id: str):
    return _current_model_id.set(model_id)


def _reset_model_id(token) -> None:
    _current_model_id.reset(token)


def multiplexed(func: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a model-loader method: results are cached per replica
    in an LRU of ``max_num_models_per_replica`` entries (reference:
    serve/multiplex.py _ModelMultiplexWrapper.load_model).

    Usage::

        @serve.deployment
        class ModelHost:
            @serve.multiplexed(max_num_models_per_replica=2)
            async def get_model(self, model_id: str):
                return load_model_weights(model_id)

            async def __call__(self, x):
                model = await self.get_model(serve.get_multiplexed_model_id())
                return model(x)

    Evicted models are dropped from the cache; if the model object has a
    ``__del__`` it runs then (matching the reference's unload semantics).
    """

    def deco(fn: Callable):
        cache_attr = f"__mux_cache_{fn.__name__}"

        if inspect.iscoroutinefunction(fn):
            @functools.wraps(fn)
            async def wrapper(self, model_id: str):
                cache: OrderedDict = getattr(self, cache_attr, None)
                if cache is None:
                    cache = OrderedDict()
                    setattr(self, cache_attr, cache)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = await fn(self, model_id)
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                return model
        else:
            @functools.wraps(fn)
            def wrapper(self, model_id: str):
                cache: OrderedDict = getattr(self, cache_attr, None)
                if cache is None:
                    cache = OrderedDict()
                    setattr(self, cache_attr, cache)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = fn(self, model_id)
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)
                return model

        return wrapper

    if func is not None:
        return deco(func)
    return deco
