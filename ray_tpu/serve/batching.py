"""Request batching for async replica methods.

Role-equivalent to the reference's @serve.batch
(reference: python/ray/serve/batching.py — concurrent calls queue up and one
invocation receives the whole batch; results fan back out).  TPU-first
rationale: model replicas want batched device calls, so the batcher is the
bridge between per-request handles and batched jit-compiled inference.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float, fn_name: str = ""):
        from ..util.metrics import get_gauge, get_histogram

        self.fn = fn
        self.fn_name = fn_name or getattr(fn, "__name__", "batch")
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.queue: Optional[asyncio.Queue] = None
        self._task: Optional[asyncio.Task] = None
        import os

        self._m_size = get_histogram(
            "ray_tpu_serve_batch_size",
            "Items per @serve.batch invocation",
            boundaries=(1, 2, 4, 8, 16, 32, 64, 128),
            tag_keys=("fn",))
        # The depth gauge carries a pid tag: each replica process runs its
        # own batcher and same-(name, tags) gauges merge last-writer-wins
        # at the head.
        self._m_depth = get_gauge(
            "ray_tpu_serve_batch_queue_depth",
            "Requests waiting in the batcher queue",
            tag_keys=("fn", "pid"))
        self._m_tags = {"fn": self.fn_name}
        self._m_depth_tags = {"fn": self.fn_name, "pid": str(os.getpid())}

    def _ensure_loop_state(self):
        if self.queue is None:
            self.queue = asyncio.Queue()
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def _loop(self):
        while True:
            item = await self.queue.get()
            batch: List = [item]
            deadline = asyncio.get_running_loop().time() + self.timeout
            while len(batch) < self.max_batch_size:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(self.queue.get(), remaining)
                    )
                except asyncio.TimeoutError:
                    break
            args = [b[0] for b in batch]
            futs = [b[1] for b in batch]
            self._m_size.observe(len(batch), tags=self._m_tags)
            self._m_depth.set(self.queue.qsize(), tags=self._m_depth_tags)
            try:
                results = await self.fn(args)
                if len(results) != len(args):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} "
                        f"results for a batch of {len(args)}"
                    )
                for f, r in zip(futs, results):
                    if not f.done():
                        f.set_result(r)
            except BaseException as e:  # noqa: BLE001 — fan the error out
                for f in futs:
                    if not f.done():
                        f.set_exception(e)

    async def __call__(self, item: Any):
        self._ensure_loop_state()
        fut = asyncio.get_running_loop().create_future()
        await self.queue.put((item, fut))
        return await fut


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: `async def method(self, item)` becomes batched — the
    wrapped function is invoked as `fn(self, [items])` and must return a
    list of the same length."""

    def deco(fn):
        # The batcher lives ON the instance (not an id()-keyed side table:
        # ids recycle after GC and a side table would pin instances forever).
        attr = f"__serve_batcher_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(self, item):
            b = getattr(self, attr, None)
            if b is None:
                async def call(items):
                    return await fn(self, items)

                b = _Batcher(call, max_batch_size, batch_wait_timeout_s,
                             fn_name=fn.__name__)
                setattr(self, attr, b)
            return await b(item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
