"""ray_tpu.serve: model serving on the actor runtime.

Role-equivalent to Ray Serve (reference: python/ray/serve — controller
reconcile loop, replica actors, power-of-two routing, batching, HTTP
ingress, request-based autoscaling), TPU-first: replicas reserve chips via
ray_actor_options and batch requests into jit-compiled inference calls.
"""

from .api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start_http,
    status,
    stop_http,
)
from .batching import batch
from .config import deploy as deploy_config
from .engine import (
    EngineConfig,
    EngineOverloadedError,
    InferenceEngine,
    LLMServer,
    llm_app,
)
from .grpc_ingress import start_grpc, stop_grpc
from .handle import DeploymentHandle, DeploymentResponse
from .multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "deployment", "Deployment", "Application", "run", "delete", "status",
    "shutdown", "get_deployment_handle", "DeploymentHandle",
    "DeploymentResponse", "batch", "start_http", "stop_http",
    "multiplexed", "get_multiplexed_model_id", "deploy_config",
    "start_grpc", "stop_grpc",
    "EngineConfig", "EngineOverloadedError", "InferenceEngine",
    "LLMServer", "llm_app",
]
