"""ray_tpu.serve: model serving on the actor runtime.

Role-equivalent to Ray Serve (reference: python/ray/serve — controller
reconcile loop, replica actors, power-of-two routing, batching, HTTP
ingress, request-based autoscaling), TPU-first: replicas reserve chips via
ray_actor_options and batch requests into jit-compiled inference calls.
"""

from .api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    run,
    shutdown,
    start_http,
    status,
    stop_http,
)
from .batching import batch
from .config import deploy as deploy_config
from .adapter_pool import AdapterNotFoundError, AdapterPool
from .engine import (
    EngineConfig,
    EngineOverloadedError,
    InferenceEngine,
    LLMServer,
    llm_app,
    random_lora,
)
from .grpc_ingress import start_grpc, stop_grpc
from .handle import DeploymentHandle, DeploymentResponse
from .multiplex import (
    get_multiplexed_model_id,
    multiplexed,
    pick_replica_for_model,
)
from .prefix_cache import RadixPrefixCache

__all__ = [
    "deployment", "Deployment", "Application", "run", "delete", "status",
    "shutdown", "get_deployment_handle", "DeploymentHandle",
    "DeploymentResponse", "batch", "start_http", "stop_http",
    "multiplexed", "get_multiplexed_model_id", "pick_replica_for_model",
    "deploy_config", "start_grpc", "stop_grpc",
    "EngineConfig", "EngineOverloadedError", "InferenceEngine",
    "LLMServer", "llm_app", "random_lora",
    "AdapterPool", "AdapterNotFoundError", "RadixPrefixCache",
]
