"""gRPC ingress for Serve.

Role-equivalent to the reference's gRPCProxy (reference:
serve/_private/proxy.py:545 gRPCProxy routed beside the HTTP proxy) —
re-designed without protobuf codegen: one generic unary method,

    /ray_tpu.serve.ServeAPI/Call

whose request/response bodies are JSON bytes::

    request:  {"deployment": "Name", "method": "__call__",
               "args": [...], "kwargs": {...},
               "multiplexed_model_id": ""}
    response: {"result": <json>}

Application errors surface as gRPC INTERNAL status with the exception
text; unknown deployments as NOT_FOUND.  Any gRPC client in any language
can call it with a bytes-in/bytes-out stub — no generated code needed on
either side.
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Dict, Optional

CALL_METHOD = "/ray_tpu.serve.ServeAPI/Call"
# Server-streaming variant: same request body, one JSON frame per item the
# generator deployment yields: {"item": <json>} ... {"done": true}
# (reference: proxy.py:537-598 — the gRPC proxy's streaming responses are
# the main reason a model server wants gRPC: token streaming).
CALL_STREAM_METHOD = "/ray_tpu.serve.ServeAPI/CallStream"


class _GrpcIngress:
    def __init__(self, host: str, port: int):
        import grpc

        from .handle import DeploymentHandle

        # LRU-bounded: one entry per (deployment, method, model_id) route;
        # unbounded model-id fan-out must not grow the dict forever.  The
        # lock guards the OrderedDict against the gRPC thread pool
        # (get/move_to_end/popitem are not a single atomic step).
        import threading
        from collections import OrderedDict

        handles: "OrderedDict[tuple, DeploymentHandle]" = OrderedDict()
        handles_lock = threading.Lock()
        max_handles = 256

        def _abort_for(e: BaseException, context):
            """Shared exception -> gRPC status mapping for both methods."""
            if isinstance(e, RuntimeError) and "no running replicas" in str(e):
                context.abort(grpc.StatusCode.NOT_FOUND, str(e))
            context.abort(grpc.StatusCode.INTERNAL,
                          f"{type(e).__name__}: {e}")

        def call(request: bytes, context):
            from ray_tpu.util import tracing

            req, h = _route(request, context)
            try:
                # Per-request root span (head-configured sampling;
                # "force_trace": true in the body is the per-call
                # override); the trace id travels back in the trailing
                # metadata for `python -m ray_tpu trace <id>`.
                with tracing.trace(
                    f"ingress:{req['deployment']}",
                    force=bool(req.get("force_trace")), proto="grpc",
                ) as tctx:
                    # Metadata set BEFORE the call: a failing request —
                    # the one worth `ray_tpu trace`-ing — must still
                    # return its trace id with the error status.
                    if tctx.get("trace_id"):
                        context.set_trailing_metadata(
                            (("x-rt-trace-id", tctx["trace_id"]),))
                    result = h.remote(
                        *(req.get("args") or []),
                        **(req.get("kwargs") or {})
                    ).result()
                # Serialize inside the mapping too: a non-JSON result
                # (arrays, bytes) must answer INTERNAL with the reason,
                # not a blank UNKNOWN.
                return json.dumps({"result": result}).encode()
            except Exception as e:  # noqa: BLE001 — mapped to a status
                _abort_for(e, context)

        def _route(request: bytes, context, stream: bool = False):
            """Shared request parse + handle lookup for both methods.
            Stream-mode handles cache separately so their p2c load counts
            persist across requests."""
            try:
                req = json.loads(request)
                if not isinstance(req, dict):
                    raise TypeError(
                        f"body must be a JSON object, got "
                        f"{type(req).__name__}")
                name = req["deployment"]
            except (ValueError, KeyError, TypeError) as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              f"bad request body: {e}")
            key = (name, req.get("method", "__call__"),
                   req.get("multiplexed_model_id", ""), stream)
            with handles_lock:
                h = handles.get(key)
                if h is not None:
                    handles.move_to_end(key)
            if h is None:
                from .api import status as serve_status

                try:
                    known = serve_status()
                except Exception:
                    known = None
                if known is not None and name not in known:
                    context.abort(grpc.StatusCode.NOT_FOUND,
                                  f"no deployment named {name!r}")
                h = DeploymentHandle(
                    name, key[1], multiplexed_model_id=key[2],
                    stream=stream)
                with handles_lock:
                    h = handles.setdefault(key, h)
                    handles.move_to_end(key)
                    while len(handles) > max_handles:
                        handles.popitem(last=False)
            return req, h

        def call_stream(request: bytes, context):
            """unary_stream: one response frame per generator item.  The
            stream is pulled item-by-item (consumer-side buffering is one
            item; the rest waits in the object store), so a slow client
            applies backpressure to this worker thread only."""
            import os
            import time

            from ray_tpu.util import tracing

            req, h = _route(request, context, stream=True)
            stream = None
            completed = False
            # Root span WITHOUT the trace() context manager: this is a
            # generator the gRPC server may resume on different pool
            # threads, and a contextvar held across yields would leak the
            # request's context into unrelated work on the opening
            # thread.  Install the context only around the same-thread
            # submission (where propagation happens); emit the ingress
            # span manually at finalization.
            span_ctx = None
            start = time.time()
            if tracing.should_sample(bool(req.get("force_trace"))):
                span_ctx = {"trace_id": tracing.new_id(),
                            "span_id": tracing.new_id()}
                context.set_trailing_metadata(
                    (("x-rt-trace-id", span_ctx["trace_id"]),))
            try:
                token = tracing.set_context(span_ctx) if span_ctx else None
                try:
                    stream = h.remote(
                        *(req.get("args") or []),
                        **(req.get("kwargs") or {}))
                finally:
                    if token is not None:
                        tracing.reset_context(token)
                for item in stream:
                    if not context.is_active():
                        return  # client cancelled between frames
                    yield json.dumps({"item": item}).encode()
                yield json.dumps({"done": True}).encode()
                completed = True
            except Exception as e:  # noqa: BLE001 — mapped to a status
                _abort_for(e, context)
            finally:
                if span_ctx is not None:
                    tracing.emit_span({
                        "trace_id": span_ctx["trace_id"],
                        "span_id": span_ctx["span_id"],
                        "parent_id": None,
                        "name": f"ingress:{req['deployment']}",
                        "start": start,
                        "end": time.time(),
                        "pid": os.getpid(),
                        "attrs": {"proto": "grpc", "stream": True,
                                  "completed": completed},
                    })
                # Any non-complete exit — the is_active() poll, a client
                # cancellation surfacing AT the yield (grpc closes this
                # generator: GeneratorExit, a BaseException), or an abort
                # — cancels the replica-side generator so an
                # engine-backed deployment frees its KV pages mid-flight.
                if stream is not None and not completed:
                    stream.cancel()

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == CALL_METHOD:
                    return grpc.unary_unary_rpc_method_handler(
                        call,
                        request_deserializer=None,   # raw bytes
                        response_serializer=None,
                    )
                if details.method == CALL_STREAM_METHOD:
                    return grpc.unary_stream_rpc_method_handler(
                        call_stream,
                        request_deserializer=None,
                        response_serializer=None,
                    )
                return None

        self.server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=16),
        )
        self.server.add_generic_rpc_handlers((Handler(),))
        self.port = self.server.add_insecure_port(f"{host}:{port}")
        self.server.start()

    def close(self):
        self.server.stop(grace=1).wait()


_grpc: Optional[_GrpcIngress] = None


def start_grpc(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the gRPC ingress; returns the bound port."""
    global _grpc
    if _grpc is None:
        _grpc = _GrpcIngress(host, port)
    return _grpc.port


def stop_grpc() -> None:
    global _grpc
    if _grpc is not None:
        _grpc.close()
        _grpc = None
