"""ServeReplica: hosts one copy of a deployment's user callable.

Role-equivalent to the reference's ReplicaActor
(reference: serve/_private/replica.py:231 — runs the user class, exposes a
queue-length probe used by the power-of-two router).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any

import cloudpickle

import ray_tpu


@ray_tpu.remote(max_concurrency=16)
class ServeReplica:
    def __init__(self, deployment_name: str, cls_blob: bytes,
                 init_args_blob: bytes):
        self.deployment_name = deployment_name
        cls = cloudpickle.loads(cls_blob)
        args, kwargs = cloudpickle.loads(init_args_blob)
        self.user = cls(*args, **kwargs) if inspect.isclass(cls) else None
        self.user_fn = None if self.user is not None else cls
        self._ongoing = 0
        self._count_lock = threading.Lock()

    def ping(self) -> str:
        return "ok"

    def queue_len(self) -> int:
        """Outstanding requests (reference: the router's queue-length probe,
        pow_2_scheduler.py)."""
        return self._ongoing

    async def handle_request(self, method: str, args: tuple, kwargs: dict,
                             model_id: str = ""):
        from .multiplex import _reset_model_id, _set_model_id

        with self._count_lock:
            self._ongoing += 1
        token = _set_model_id(model_id)
        try:
            if self.user_fn is not None:
                target = self.user_fn
            elif method == "__call__":
                target = self.user
            else:
                target = getattr(self.user, method)
            if inspect.iscoroutinefunction(target):
                return await target(*args, **kwargs)
            # Sync callables run off-loop: blocking user code must not stall
            # the replica's event loop (concurrent requests keep flowing and
            # queue pressure stays observable for autoscaling).  The model-id
            # contextvar rides along via copy_context.
            import contextvars as _cv

            ctx = _cv.copy_context()
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: ctx.run(target, *args, **kwargs)
            )
            if inspect.iscoroutine(out):
                out = await out
            return out
        finally:
            _reset_model_id(token)
            with self._count_lock:
                self._ongoing -= 1
