"""ServeReplica: hosts one copy of a deployment's user callable.

Role-equivalent to the reference's ReplicaActor
(reference: serve/_private/replica.py:231 — runs the user class, exposes a
queue-length probe used by the power-of-two router).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any

import cloudpickle

import ray_tpu


@ray_tpu.remote(max_concurrency=16)
class ServeReplica:
    def __init__(self, deployment_name: str, cls_blob: bytes,
                 init_args_blob: bytes):
        import os

        from ..util.metrics import get_gauge, get_histogram

        self.deployment_name = deployment_name
        cls = cloudpickle.loads(cls_blob)
        args, kwargs = cloudpickle.loads(init_args_blob)
        self.user = cls(*args, **kwargs) if inspect.isclass(cls) else None
        self.user_fn = None if self.user is not None else cls
        self._ongoing = 0
        self._count_lock = threading.Lock()
        # Auto-instrumentation, hoisted off the request path (instrument
        # lookup takes the process-global registry lock).  Queue depth
        # carries a pid tag: two replicas of one deployment must stay
        # distinct series (the head's gauge merge is last-writer-wins
        # per (name, tags)); the latency histogram sums safely across
        # replicas so deployment alone suffices.
        self._m_latency = get_histogram(
            "ray_tpu_serve_request_latency_seconds",
            "Serve request handling latency per deployment",
            boundaries=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10),
            tag_keys=("deployment",))
        self._m_depth = get_gauge(
            "ray_tpu_serve_replica_queue_depth",
            "In-flight requests on this replica",
            tag_keys=("deployment", "pid"))
        self._m_tags = {"deployment": deployment_name}
        self._m_depth_tags = {"deployment": deployment_name,
                              "pid": str(os.getpid())}

    def ping(self) -> str:
        return "ok"

    def queue_len(self) -> int:
        """Outstanding requests (reference: the router's queue-length probe,
        pow_2_scheduler.py)."""
        return self._ongoing

    def _resolve_target(self, method: str):
        if self.user_fn is not None:
            return self.user_fn
        if method == "__call__":
            return self.user
        return getattr(self.user, method)

    def _request_scope(self, model_id: str):
        """Ongoing-count + multiplex-model-id bracket shared by the unary
        and streaming paths.  Also the replica's auto-instrumentation
        point: request latency histogram + queue-depth gauge (instruments
        created in __init__; reference: serve's
        ray_serve_deployment_request_* via the replica's metrics pusher)."""
        import contextlib
        import time as _time

        from ..util import tracing
        from .multiplex import _reset_model_id, _set_model_id

        @contextlib.contextmanager
        def scope():
            with self._count_lock:
                self._ongoing += 1
                self._m_depth.set(self._ongoing, tags=self._m_depth_tags)
            token = _set_model_id(model_id)
            start = _time.perf_counter()
            try:
                # Per-request replica span: nests under the propagated
                # execution span when the caller traced (ingress, handle,
                # or an explicit tracing.trace) — the engine's
                # queue/prefill/decode tree hangs off it.  Propagation-
                # only: untraced/unsampled requests stay span-free.
                with tracing.trace_if_active(
                    f"replica:{self.deployment_name}",
                    **({"model_id": model_id} if model_id else {}),
                ):
                    yield
            finally:
                self._m_latency.observe(_time.perf_counter() - start,
                                        tags=self._m_tags)
                _reset_model_id(token)
                with self._count_lock:
                    self._ongoing -= 1
                    self._m_depth.set(self._ongoing, tags=self._m_depth_tags)

        return scope()

    async def handle_request(self, method: str, args: tuple, kwargs: dict,
                             model_id: str = ""):
        with self._request_scope(model_id):
            target = self._resolve_target(method)
            if inspect.iscoroutinefunction(target):
                return await target(*args, **kwargs)
            # Sync callables run off-loop: blocking user code must not stall
            # the replica's event loop (concurrent requests keep flowing and
            # queue pressure stays observable for autoscaling).  The model-id
            # contextvar rides along via copy_context.
            import contextvars as _cv

            ctx = _cv.copy_context()
            out = await asyncio.get_running_loop().run_in_executor(
                None, lambda: ctx.run(target, *args, **kwargs)
            )
            if inspect.iscoroutine(out):
                out = await out
            return out

    def handle_request_streaming(self, method: str, args: tuple,
                                 kwargs: dict, model_id: str = ""):
        """Generator execution path: the user generator's items flow out
        through the core streaming-returns channel one at a time
        (reference: replica.py handle_request_streaming — the proxy and
        handles consume an ObjectRefGenerator).  Runs as a SYNC generator
        on the actor's thread pool, so a slow stream occupies one lane
        while other requests keep flowing.  Called with
        num_returns="streaming" by the handle layer."""
        with self._request_scope(model_id):
            target = self._resolve_target(method)
            if inspect.iscoroutinefunction(target) or \
                    inspect.isasyncgenfunction(target):
                raise TypeError(
                    "streaming deployments must use sync generators "
                    "(async callables would need the replica's event "
                    "loop, which belongs to unary async requests)")
            out = target(*args, **kwargs)
            if inspect.isasyncgen(out) or inspect.iscoroutine(out):
                if inspect.iscoroutine(out):
                    out.close()  # never awaited by design
                raise TypeError(
                    "streaming deployments must use sync generators "
                    "(async generators would need the replica's event "
                    "loop, which belongs to unary async requests)")
            if inspect.isgenerator(out) or (
                    hasattr(out, "__iter__")
                    and not isinstance(out, (str, bytes, dict))):
                for item in out:
                    yield item
            else:
                yield out  # non-generator: a one-item stream
