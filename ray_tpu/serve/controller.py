"""ServeController: the deployment reconcile loop.

Role-equivalent to the reference's ServeController
(reference: serve/_private/controller.py:86 run_control_loop:372 +
deployment_state.py:2312 DeploymentStateManager): holds target state per
deployment, reconciles actual replica actors toward it (create on deploy /
scale-up, drain on scale-down, replace on death), and serves routing tables
to handles.  Request-based autoscaling compares reported queue pressure to
target (reference: autoscaling_state.py).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "SERVE_CONTROLLER"


def _publish_slo(name: str, spec: Optional[dict]):
    """Mirror a deployment's latency SLO targets into the head KV
    (``serve_slo:<deployment>``) so the head's health engine can run SLO
    burn-rate detection without a serve import.  ``spec=None`` clears the
    key on undeploy.  Best-effort: KV hiccups must not fail deploy()."""
    try:
        from ray_tpu.core.context import ctx
        if ctx.client is None:
            return
        key = f"serve_slo:{name}"
        targets: Dict[str, float] = {}
        auto = (spec or {}).get("autoscaling") or {}
        ttft = auto.get("target_ttft_s")
        itl = auto.get("target_itl_s")
        if ttft:
            targets["ttft"] = float(ttft)
        if itl:
            targets["itl"] = float(itl)
        if spec is not None and targets:
            ctx.client.kv_put(key, json.dumps(targets).encode())
        else:
            ctx.client.kv_del(key)
    except Exception:
        pass


def _scale_decision(cur: int, min_r: int, max_r: int,
                    per_queue: float, target_q: float,
                    ttft_p90: Optional[float] = None,
                    target_ttft: Optional[float] = None,
                    stall_frac: Optional[float] = None,
                    target_stall_frac: float = 0.25) -> int:
    """Pure scaling decision (unit-testable without actors): breach of
    ANY signal scales up; scale-down needs ALL comfortably idle.
    TTFT is the user-facing SLO — queue depth alone under-scales an
    engine whose batch is full but whose queue drains slowly (every
    admitted sequence decodes for many steps, so a short queue can still
    mean seconds of time-to-first-token).  ``stall_frac`` is the engine's
    admission-stall pressure (InferenceEngine.slo_signals, fraction of
    the window the decode loop spent stalled on prefills): a saturated
    engine stalls BEFORE TTFT breaches, so reacting to it scales ahead
    of the user-visible miss."""
    breach = per_queue > target_q or (
        target_ttft is not None and ttft_p90 is not None
        and ttft_p90 > target_ttft) or (
        stall_frac is not None and stall_frac > target_stall_frac)
    idle = per_queue < target_q / 2 and (
        target_ttft is None or ttft_p90 is None
        or ttft_p90 < target_ttft / 2) and (
        stall_frac is None or stall_frac < target_stall_frac / 2)
    if breach and cur < max_r:
        return cur + 1
    if idle and not breach and cur > min_r:
        return cur - 1
    return cur


@ray_tpu.remote(max_concurrency=8)
class ServeController:
    def __init__(self):
        # name -> target spec dict
        self.targets: Dict[str, dict] = {}
        # name -> list of {"handle": ActorHandle, "id": int}
        self.replicas: Dict[str, List[dict]] = {}
        self._next_replica_id = 0
        self._lock = threading.Lock()
        self._version = 0
        self._shutdown = False
        threading.Thread(target=self._control_loop, daemon=True,
                         name="serve-reconcile").start()

    # -- API -----------------------------------------------------------------

    def deploy(self, name: str, spec: dict) -> bool:
        """Set a deployment's target (create or update).  spec: cls_blob,
        init_args_blob, num_replicas, max_concurrent, resources,
        autoscaling (optional {min_replicas, max_replicas,
        target_ongoing_requests})."""
        with self._lock:
            old = self.targets.get(name)
            spec = dict(spec)
            spec["version"] = (old["version"] + 1) if old else 1
            self.targets[name] = spec
            self._version += 1
        _publish_slo(name, spec)
        return True

    def delete(self, name: str) -> bool:
        with self._lock:
            self.targets.pop(name, None)
            self._version += 1
        _publish_slo(name, None)
        return True

    def routing_table(self) -> dict:
        """Replica actor handles per deployment (handles reconstruct
        actor refs on the receiving side).  ``replica_ids`` carries the
        stable controller-issued id per replica, position-aligned with
        ``deployments`` — handles feed them to rendezvous hashing so
        model affinity survives scale events."""
        with self._lock:
            return {
                "version": self._version,
                "deployments": {
                    name: [r["handle"] for r in reps]
                    for name, reps in self.replicas.items()
                },
                "replica_ids": {
                    name: [r["id"] for r in reps]
                    for name, reps in self.replicas.items()
                },
            }

    def status(self) -> dict:
        with self._lock:
            return {
                name: {
                    "target_replicas": self._target_replicas(name),
                    "running_replicas": len(self.replicas.get(name, [])),
                    "version": spec["version"],
                }
                for name, spec in self.targets.items()
            }

    def ready(self, name: str) -> bool:
        with self._lock:
            spec = self.targets.get(name)
            if spec is None:
                return False
            # Only CURRENT-version replicas count: a redeploy isn't ready
            # while old-code replicas still serve.
            current = [
                r for r in self.replicas.get(name, [])
                if r["version"] == spec["version"]
            ]
            return len(current) >= max(1, self._target_replicas(name))

    def shutdown(self) -> bool:
        with self._lock:
            self._shutdown = True
            self.targets.clear()
        return True

    # -- reconcile -----------------------------------------------------------

    def _target_replicas(self, name: str) -> int:
        spec = self.targets.get(name)
        if spec is None:
            return 0
        auto = spec.get("autoscaling")
        if auto:
            return spec.get("_autoscaled", auto["min_replicas"])
        return spec.get("num_replicas", 1)

    def _control_loop(self):
        from .replica import ServeReplica

        while True:
            time.sleep(0.2)
            with self._lock:
                if self._shutdown and not any(self.replicas.values()):
                    break
                targets = dict(self.targets)
            # Drop deployments no longer targeted.
            for name in list(self.replicas):
                if name not in targets:
                    with self._lock:
                        dropped = self.replicas.pop(name, [])
                        self._version += 1
                    for r in dropped:
                        self._stop_replica(r)
            for name, spec in targets.items():
                with self._lock:
                    reps = list(self.replicas.get(name, ()))
                # Replace dead replicas and version-mismatched ones
                # (rolling update: new code/config -> new actors).  Health
                # probes go out in parallel; stragglers past the deadline
                # count as dead (a single hung replica must not stall the
                # loop for every deployment).
                changed = False
                alive_flags = self._alive_many(reps)
                live = []
                for r, ok in zip(reps, alive_flags):
                    if r["version"] != spec["version"] or not ok:
                        self._stop_replica(r)
                        changed = True
                    else:
                        live.append(r)
                reps = live
                self._autoscale(name, spec, reps)
                want = self._target_replicas(name)
                while len(reps) < want:
                    try:
                        reps.append(self._start_replica(name, spec))
                        changed = True
                    except Exception:
                        break
                while len(reps) > want:
                    self._stop_replica(reps.pop())
                    changed = True
                with self._lock:
                    if name in self.targets:
                        self.replicas[name] = reps
                    if changed:
                        self._version += 1

    def _alive_many(self, reps: List[dict]) -> List[bool]:
        if not reps:
            return []
        try:
            refs = [r["handle"].ping.remote() for r in reps]
        except Exception:
            return [False] * len(reps)
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=5)
        ready_set = set(ready)
        out = []
        for ref in refs:
            if ref not in ready_set:
                out.append(False)  # straggler past the deadline
                continue
            try:
                out.append(ray_tpu.get(ref, timeout=1) == "ok")
            except Exception:
                out.append(False)  # sealed with ActorDiedError etc.
        return out

    def _start_replica(self, name: str, spec: dict) -> dict:
        from .replica import ServeReplica

        self._next_replica_id += 1
        opts: Dict[str, Any] = {
            "max_concurrency": spec.get("max_concurrent", 8),
            "name": f"SERVE_REPLICA:{name}#{self._next_replica_id}",
        }
        res = spec.get("resources") or {}
        if res.get("CPU") is not None:
            opts["num_cpus"] = res["CPU"]
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        handle = ServeReplica.options(**opts).remote(
            name, spec["cls_blob"], spec["init_args_blob"]
        )
        ray_tpu.get(handle.ping.remote(), timeout=120)  # wait ready
        return {"handle": handle, "id": self._next_replica_id,
                "version": spec["version"]}

    def _stop_replica(self, r: dict):
        try:
            ray_tpu.kill(r["handle"])
        except Exception:
            pass

    def _autoscale(self, name: str, spec: dict, reps: List[dict]):
        auto = spec.get("autoscaling")
        if not auto:
            return
        if not reps:
            spec.setdefault("_autoscaled", auto["min_replicas"])
            return
        # SLO path: when the deployment declares target_ttft_s, ask each
        # replica's user callable for engine signals (LLMServer
        # .engine_metrics -> InferenceEngine.slo_signals) and scale on
        # queue depth + recent TTFT p90.  Non-engine replicas (or a
        # signal call that fails) fall back to the queue-length probe.
        total_q = 0.0
        ttfts: List[float] = []
        stalls: List[float] = []
        target_ttft = auto.get("target_ttft_s")
        for r in reps:
            sig = None
            if target_ttft is not None:
                try:
                    sig = ray_tpu.get(
                        r["handle"].handle_request.remote(
                            "engine_metrics", (), {}),
                        timeout=5)
                except Exception:
                    sig = None
            if isinstance(sig, dict):
                total_q += sig.get("queue_depth", 0)
                if sig.get("ttft_p90_s") is not None:
                    ttfts.append(sig["ttft_p90_s"])
                if sig.get("stall_frac") is not None:
                    stalls.append(sig["stall_frac"])
                continue
            try:
                total_q += ray_tpu.get(r["handle"].queue_len.remote(),
                                       timeout=5)
            except Exception:
                pass
        per = total_q / max(1, len(reps))
        target = auto.get("target_ongoing_requests", 2)
        cur = spec.get("_autoscaled", auto["min_replicas"])
        cur = _scale_decision(
            cur, auto["min_replicas"], auto["max_replicas"], per, target,
            max(ttfts) if ttfts else None, target_ttft,
            max(stalls) if stalls else None,
            auto.get("target_stall_frac", 0.25))
        spec["_autoscaled"] = cur
        with self._lock:
            if name in self.targets:
                self.targets[name]["_autoscaled"] = cur


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        try:
            return ServeController.options(
                name=CONTROLLER_NAME, num_cpus=0
            ).remote()
        except Exception:
            # Raced another creator: the name is taken now.
            return ray_tpu.get_actor(CONTROLLER_NAME)
