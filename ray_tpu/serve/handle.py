"""DeploymentHandle: route requests to replicas.

Role-equivalent to the reference's DeploymentHandle -> Router ->
PowerOfTwoChoicesReplicaScheduler chain
(reference: serve/handle.py:729 .remote, _private/router.py:560
assign_request, replica_scheduler/pow_2_scheduler.py:51): two random
replicas are compared by queue pressure and the less-loaded one gets the
request.  The routing table refreshes from the controller when its version
changes (the long-poll analog, reference: _private/long_poll.py).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

# Replica-death retry budget: total attempts a request gets when its replica
# dies underneath it (rolling update, crash) before the error surfaces.
# Shared by the unary path (DeploymentResponse.result) and the streaming
# path (DeploymentResponseGenerator, pre-first-item only — a mid-stream
# replica death is stateful and must surface).  Every consumed retry counts
# into ``ray_tpu_serve_replica_retries_total`` (tagged by path).
REPLICA_RETRY_BUDGET = 3


def _replica_retry_policy():
    """Re-route pacing after a replica death: the unified jittered-doubling
    curve (core/deadline.py), starting where the old hand-rolled ramp did
    (200 ms) and capped at 1 s — a rolling update replaces a replica well
    within the budget, so longer waits only add tail latency."""
    from ..core.deadline import BackoffPolicy

    return BackoffPolicy(base_s=0.2, multiplier=2.0, cap_s=1.0)


def _count_replica_retry(path: str) -> None:
    from ..util.metrics import get_counter

    try:
        get_counter(
            "ray_tpu_serve_replica_retries_total",
            "Requests re-routed after a replica death",
            tag_keys=("path",),
        ).inc(1, tags={"path": path})
    except Exception:
        pass  # metrics must never fail a request


class DeploymentResponse:
    """Future-like result of handle.remote() (reference: serve/handle.py
    DeploymentResponse).  A replica dying under the request (rolling
    update, crash) re-routes it once the routing table refreshes
    (reference: the router retries failed replicas)."""

    def __init__(self, ref, done_cb=None, retry=None,
                 stall_timeout_s: Optional[float] = None, eject=None):
        self._ref = ref
        self._done_cb = done_cb
        self._retry = retry
        # Gray-failure knob (handle.options(stall_timeout_s=...)): a
        # replica holding the request past this many seconds is treated as
        # stalled — ejected from the p2c set and the request re-routed,
        # within the same REPLICA_RETRY_BUDGET that covers death.
        self._stall_timeout_s = stall_timeout_s
        self._eject = eject

    def result(self, timeout: float = 60.0):
        from ..exceptions import (ActorDiedError, GetTimeoutError,
                                  WorkerCrashedError)

        from ..core.deadline import Deadline

        deadline = Deadline.after(timeout)
        backoff = _replica_retry_policy()
        try:
            for attempt in range(REPLICA_RETRY_BUDGET):
                last = attempt == REPLICA_RETRY_BUDGET - 1
                get_timeout = timeout
                if self._stall_timeout_s is not None:
                    get_timeout = min(self._stall_timeout_s,
                                      max(0.0, deadline.remaining()))
                try:
                    return ray_tpu.get(self._ref, timeout=get_timeout)
                except (ActorDiedError, WorkerCrashedError):
                    if self._retry is None or last:
                        raise
                    _count_replica_retry("unary")
                    backoff.sleep(attempt + 1, deadline)
                    self._ref = self._retry()
                except GetTimeoutError:
                    # Stalled replica (accepts, never answers): eject it
                    # from the p2c set and re-route — unless the stall
                    # knob is off (then the timeout is the caller's own)
                    # or the overall deadline is spent anyway.
                    if (self._stall_timeout_s is None or self._retry is None
                            or last
                            or deadline.remaining()
                            <= self._stall_timeout_s):
                        raise
                    if self._eject is not None:
                        self._eject()
                    _count_replica_retry("stall")
                    self._ref = self._retry()
        finally:
            if self._done_cb is not None:
                self._done_cb()
                self._done_cb = None

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Iterator over a streaming deployment call's items (reference:
    serve/handle.py DeploymentResponseGenerator over an
    ObjectRefGenerator).  Buffering is consumer-side one-item-at-a-time;
    produced-but-unconsumed items wait in the object store (spill-bounded),
    never in this process.  The REPLICA_RETRY_BUDGET applies only BEFORE
    the first item is yielded (the request is still stateless then); a
    mid-stream replica death is stateful and surfaces to the caller."""

    def __init__(self, ref_gen, done_cb=None, retry=None):
        self._gen = ref_gen
        self._done_cb = done_cb
        self._retry = retry

    def _release(self):
        if self._done_cb is not None:
            cb, self._done_cb = self._done_cb, None
            cb()

    def cancel(self) -> None:
        """Stop the replica-side generator (reference: serve's streaming
        requests cancel the underlying task when the client disconnects).
        The replica raises TaskCancelledError inside the user generator,
        so engine-backed deployments free pages mid-flight.  Idempotent;
        also releases this handle's outstanding-load count."""
        try:
            self._gen.cancel()
        except Exception:  # noqa: BLE001 — cancel must never raise at
            pass           # teardown (task may already be finished)
        self._release()

    def __iter__(self):
        from ..exceptions import ActorDiedError, WorkerCrashedError

        try:
            yielded = False
            attempt = 0
            backoff = _replica_retry_policy()
            while True:
                try:
                    for ref in self._gen:
                        yield ray_tpu.get(ref)
                        yielded = True
                    return
                except (ActorDiedError, WorkerCrashedError):
                    attempt += 1
                    if (yielded or self._retry is None
                            or attempt >= REPLICA_RETRY_BUDGET):
                        raise
                    _count_replica_retry("streaming")
                    backoff.sleep(attempt)
                    self._gen = self._retry()
        finally:
            self._release()

    def __del__(self):
        # A stream created but never iterated must still release its
        # replica's outstanding-load count, or p2c routing skews away from
        # that replica until the next routing-table version bump.
        try:
            self._release()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str, method: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 stall_timeout_s: Optional[float] = None):
        self.deployment_name = deployment_name
        self.method = method
        self.multiplexed_model_id = multiplexed_model_id
        self.stream = stream
        # Opt-in stalled-replica detection: a unary request unanswered for
        # this long ejects its replica from the p2c set and re-routes
        # (None = off; a replica can legitimately be slow).
        self.stall_timeout_s = stall_timeout_s
        self._replicas: List[Any] = []
        self._replica_ids: List[int] = []
        self._version = -1
        self._last_refresh = 0.0
        self._local_load: Dict[int, int] = {}  # replica idx -> outstanding
        self._ejected: Dict[int, float] = {}   # replica idx -> lift time
        self._lock = threading.Lock()

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                stall_timeout_s: Optional[float] = None
                ) -> "DeploymentHandle":
        """(reference: serve/handle.py .options — method_name,
        multiplexed_model_id, stream and stall_timeout_s are the supported
        knobs here; stream=True makes .remote() return a
        DeploymentResponseGenerator over a generator deployment's
        items)."""
        return DeploymentHandle(
            self.deployment_name,
            method_name if method_name is not None else self.method,
            multiplexed_model_id if multiplexed_model_id is not None
            else self.multiplexed_model_id,
            stream if stream is not None else self.stream,
            stall_timeout_s if stall_timeout_s is not None
            else self.stall_timeout_s,
        )

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        with self._lock:
            if not force and self._replicas and now - self._last_refresh < 1.0:
                return
        from .controller import get_or_create_controller

        controller = get_or_create_controller()
        table = ray_tpu.get(controller.routing_table.remote(), timeout=30)
        with self._lock:
            if table["version"] != self._version:
                self._replicas = table["deployments"].get(
                    self.deployment_name, []
                )
                self._replica_ids = table.get("replica_ids", {}).get(
                    self.deployment_name, []
                )
                self._version = table["version"]
                self._local_load = {i: 0 for i in range(len(self._replicas))}
                # Indexes shifted with the table: stale ejections would
                # punish whichever replica inherited the slot.
                self._ejected = {}
            self._last_refresh = now

    def _pick(self) -> int:
        """Power-of-two-choices on the handle's local outstanding counts
        (the client-side view of queue pressure).  Multiplexed requests get
        rendezvous-hash affinity over the controller's STABLE replica ids
        instead: a model id sticks to one replica so repeated requests hit
        its warm LRU, and adding/removing a replica remaps only the models
        that must move (modulus hashing over list positions reshuffled
        nearly every model on any scale event, stranding every warm
        cache)."""
        n = len(self._replicas)
        now = time.monotonic()
        if self._ejected:
            for i in [i for i, lift in self._ejected.items() if now >= lift]:
                self._ejected.pop(i, None)  # lift: the next pick re-probes
        if n == 1:
            return 0
        if self.multiplexed_model_id:
            from .multiplex import pick_replica_for_model

            ids = self._replica_ids if len(self._replica_ids) == n \
                else list(range(n))
            return pick_replica_for_model(self.multiplexed_model_id, ids)
        # Stalled replicas sit out of the candidate set until their lift
        # time — unless everything is ejected, in which case degrading to
        # the full set beats refusing the request.
        avail = [i for i in range(n) if i not in self._ejected] \
            if self._ejected else list(range(n))
        if not avail:
            avail = list(range(n))
        if len(avail) == 1:
            return avail[0]
        i, j = random.sample(avail, 2)
        return i if self._local_load.get(i, 0) <= self._local_load.get(j, 0) \
            else j

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        deadline = time.monotonic() + 30
        while True:
            self._refresh()
            with self._lock:
                if self._replicas:
                    idx = self._pick()
                    replica = self._replicas[idx]
                    self._local_load[idx] = self._local_load.get(idx, 0) + 1
                    break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"deployment {self.deployment_name!r} has no running "
                    "replicas"
                )
            time.sleep(0.1)
            self._refresh(force=True)

        state = {"idx": idx}

        def done():
            with self._lock:
                i = state["idx"]
                if i in self._local_load:
                    self._local_load[i] = max(0, self._local_load[i] - 1)

        def submit(rep):
            if self.stream:
                return rep.handle_request_streaming.options(
                    num_returns="streaming"
                ).remote(self.method, args, kwargs,
                         model_id=self.multiplexed_model_id)
            return rep.handle_request.remote(
                self.method, args, kwargs,
                model_id=self.multiplexed_model_id,
            )

        # Routing span: parents the replica's execution span to the
        # ingress trace and records which replica the p2c pick chose; the
        # replica queue-wait then reads off the trace as the gap between
        # this span and the execution span.  Propagation-only — an
        # untraced caller (no ingress span, no user trace) pays nothing;
        # roots come from the ingress or an explicit tracing.trace().
        from ..util import tracing

        with tracing.trace_if_active(f"handle:{self.deployment_name}",
                                     stream=self.stream) as hspan:
            try:
                ref = submit(replica)
            except Exception:
                done()
                # Replica likely died: force-refresh and retry once.
                self._refresh(force=True)
                with self._lock:
                    if not self._replicas:
                        raise
                    idx = self._pick()
                    replica = self._replicas[idx]
                    self._local_load[idx] = self._local_load.get(idx, 0) + 1
                    # done() must release THIS replica's count, not the
                    # dead one's (already released above).
                    state["idx"] = idx
                ref = submit(replica)
            # Late attr: the FINAL pick — the in-span retry may have
            # re-routed off a dead replica, and the trace must name the
            # replica that actually got the request.
            hspan["attrs"] = {"replica": state["idx"]}

        def retry():
            self._refresh(force=True)
            with self._lock:
                if not self._replicas:
                    raise RuntimeError(
                        f"deployment {self.deployment_name!r} has no "
                        "running replicas"
                    )
                i = self._pick()
                rep = self._replicas[i]
                # Transfer the outstanding count to the retry target so the
                # p2c picker sees its real pressure; done() releases it.
                old = state["idx"]
                if old in self._local_load:
                    self._local_load[old] = max(
                        0, self._local_load[old] - 1
                    )
                self._local_load[i] = self._local_load.get(i, 0) + 1
                state["idx"] = i
            return submit(rep)

        if self.stream:
            return DeploymentResponseGenerator(ref, done, retry)

        def eject():
            with self._lock:
                lift = time.monotonic() + max(
                    5.0, 2.0 * (self.stall_timeout_s or 0.0))
                self._ejected[state["idx"]] = lift

        return DeploymentResponse(ref, done, retry,
                                  stall_timeout_s=self.stall_timeout_s,
                                  eject=eject)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.method,
                 self.multiplexed_model_id, self.stream,
                 self.stall_timeout_s))
