"""Continuous-batching LLM inference engine behind serve.

Role-equivalent to the Ray Serve LLM stack's engine loop (reference: Ray
Serve's LLM deployments wrap a continuous-batching engine; PAPER.md L7
names model multiplexing + streaming as the serve capability surface).
The engine turns a replica from a request router into an inference loop:

- ONE decode program (``models/paged.py``) serves every admission mix —
  batch slots, page tables, and lengths are data, so after warmup the
  loop never recompiles.
- Queued sequences are admitted into free batch slots BETWEEN decode
  steps; a prefill runs as its own (bucketed) program, so running
  sequences stall by at most one step per admission.
- Finished/cancelled sequences are evicted between steps and their pages
  return to the free list; the page pool's worst-case footprint is
  reserved at admission, so decode can never die of page exhaustion
  mid-flight.
- Admission control sheds with a typed :class:`EngineOverloadedError`
  when the wait queue exceeds its bound — goodput holds under overload
  instead of collapsing into unbounded queueing.
- Tokens stream out per-request as they decode (the deployment's sync
  generator feeds serve's existing per-item streaming path: handles,
  HTTP SSE, gRPC server-streaming); a consumer that disappears cancels
  the request and frees its pages mid-flight.

``mode="whole_request"`` keeps the same kernels but only admits when the
batch is EMPTY (gang admission, drain to completion) — the baseline
``bench_serve.py`` compares against.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

#: Engine identity within one process: step records carry
#: ``"<pid>.<seq>"`` so the head's per-engine rings stay distinct when a
#: process hosts several engines (bench harnesses, tests).
_ENGINE_SEQ = itertools.count()


class EngineOverloadedError(Exception):
    """Typed admission-control shed: the engine's wait queue is full.

    Callers see this at submit time (the request never held pages or a
    slot); clients should back off and retry — the standard overload
    contract (reference: Serve's backpressure returns 503)."""


@dataclasses.dataclass
class EngineConfig:
    """Sizing knobs for one replica's engine.

    ``page_table_width`` (MAXP) and the pool size derive from the prompt
    and output caps so admission's worst-case reservation always fits a
    fresh pool: ``num_pages = 0`` auto-sizes to ``batch_slots`` times the
    per-sequence worst case."""

    batch_slots: int = 8
    page_size: int = 16
    max_prompt_len: int = 64
    max_new_tokens_cap: int = 128
    num_pages: int = 0            # 0 -> batch_slots * pages_per_seq
    max_queue: int = 32           # admission bound: beyond this, shed
    mode: str = "continuous"      # or "whole_request" (gang admission)
    stream_timeout_s: float = 120.0
    # Multi-tenant plane.  max_adapters/lora_rank shape the device
    # adapter pool and are PART of the decode signature — engines that
    # should share one compiled program must agree on them (like the
    # geometry above).  prefix_cache toggles the radix tree over the
    # paged KV; ttft_window sizes the recent-TTFT deque feeding the
    # controller's SLO autoscaling.
    max_adapters: int = 4
    lora_rank: int = 8
    prefix_cache: bool = True
    ttft_window: int = 64
    # Flight recorder (util/steprec.py): one fixed-size record per decode
    # step into the bounded per-process ring.  Off-hot-path by design
    # (host counters only, no device sync); the bench_serve overhead row
    # holds it to <= 2% of step wall.  step_window sizes the recent
    # step-wall / stall deques feeding slo_signals jitter + stall
    # pressure.
    step_record: bool = True
    step_window: int = 256

    @property
    def pages_per_seq(self) -> int:
        # The page table must cover BOTH the worst-case sequence AND the
        # largest prefill bucket: padded prefill positions index the
        # table, and jit clamps an out-of-range gather to the last entry
        # — which would silently corrupt a real page.
        worst = math.ceil(
            (self.max_prompt_len + self.max_new_tokens_cap)
            / self.page_size)
        return max(worst, self.prefill_buckets()[-1] // self.page_size)

    @property
    def pool_pages(self) -> int:
        return self.num_pages or self.batch_slots * self.pages_per_seq

    def prefill_buckets(self) -> List[int]:
        """Padded prompt lengths (one compile each): page-size multiples
        doubling up to the prompt cap."""
        out, b = [], self.page_size
        while b < self.max_prompt_len:
            out.append(b)
            b *= 2
        out.append(max(b, self.max_prompt_len))
        return out


class _Request:
    __slots__ = (
        "req_id", "prompt", "max_new", "temperature", "stop_token",
        "out_q", "cancelled", "finished", "pages", "page_table",
        "length", "generated", "submit_t", "first_token_t",
        "last_token_t", "itls", "slot",
        "trace_ctx", "submit_wall", "admit_wall", "first_wall",
        "prefill_bucket",
        "tenant", "weight", "adapter", "adapter_slot", "match",
        "cow_ref", "cache_hit_len",
    )

    def __init__(self, req_id: int, prompt: np.ndarray, max_new: int,
                 temperature: float, stop_token: Optional[int]):
        self.req_id = req_id
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.stop_token = stop_token
        self.out_q: "_queue.Queue" = _queue.Queue()
        self.cancelled = threading.Event()
        self.finished = False
        self.pages: List[int] = []
        self.page_table: Optional[np.ndarray] = None
        self.length = 0
        self.generated = 0
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        # Engine-side inter-token latencies: measured at emission, so
        # they reflect decode cadence, not consumer scheduling.
        self.itls: List[float] = []
        self.slot = -1
        # Tracing: the submitter's span context (None when the request
        # arrived untraced/unsampled — then the engine emits nothing) plus
        # wall-clock transition stamps for the queue/prefill/decode spans
        # (submit_t/first_token_t are perf_counter and can't be shared
        # with wall-clocked spans from other processes).
        self.trace_ctx: Optional[Dict[str, str]] = None
        self.submit_wall = 0.0
        self.admit_wall = 0.0
        self.first_wall = 0.0
        self.prefill_bucket = 0
        # Multi-tenant plane: fair-queue identity, the adapter this
        # sequence decodes with (None = base model), and the prefix-cache
        # plan pinned at admission (match + the extra COW-source ref held
        # until the page is copied).
        self.tenant = "default"
        self.weight = 1.0
        self.adapter: Optional[str] = None
        self.adapter_slot = -1
        self.match = None
        self.cow_ref: Optional[int] = None
        self.cache_hit_len = 0


class TokenStream:
    """Per-request token iterator; the consumer side of the engine's
    emission queue.  ``cancel()`` (or closing the iterating generator)
    releases the request's slot and pages at the next step boundary."""

    def __init__(self, engine: "InferenceEngine", req: _Request):
        self._engine = engine
        self._req = req
        self.steps: List[int] = []   # decode-step index of each token
        self.ttft_s: Optional[float] = None

    def __iter__(self):
        return self

    def __next__(self) -> int:
        try:
            kind, payload, step = self._req.out_q.get(
                timeout=self._engine.config.stream_timeout_s)
        except _queue.Empty:
            self.cancel()
            raise RuntimeError(
                "engine stream stalled past stream_timeout_s") from None
        if kind == "tok":
            if self.ttft_s is None and self._req.first_token_t is not None:
                self.ttft_s = self._req.first_token_t - self._req.submit_t
            self.steps.append(step)
            return int(payload)
        if kind == "err":
            raise payload
        raise StopIteration  # ("done", reason)

    def cancel(self) -> None:
        self._engine.cancel(self._req)


class InferenceEngine:
    """One replica's decode loop: host-side sequence/slot state machine
    around the jitted paged programs.  The loop runs on a dedicated
    daemon thread; ``submit()`` is called from any number of request
    threads and only touches the wait queue under the lock — pools,
    allocator, and slot arrays belong to the loop thread alone."""

    def __init__(self, model_config, params, config: EngineConfig,
                 seed: int = 0):
        import jax

        from ..devtools import jitguard
        from ..models.paged import (PAGED_PROGRAMS, PageAllocator,
                                    init_paged_pools)
        from ..util.metrics import get_counter, get_gauge, get_histogram

        # A fresh engine means fresh geometry: re-registering stands the
        # paged programs' armed baselines down (recompile sentinel) until
        # this engine's own warmup() re-arms — an un-warmed engine's cold
        # traces are a compile phase, not hot-path recompiles.
        for prog in PAGED_PROGRAMS:
            jitguard.register_program(prog)
        self.model_config = model_config
        self.params = params
        self.config = config
        cfg = config
        self.maxp = cfg.pages_per_seq
        self.scratch = cfg.pool_pages  # scratch page index
        self.pools = init_paged_pools(model_config, cfg.pool_pages,
                                      cfg.page_size)
        self.allocator = PageAllocator(cfg.pool_pages)
        # Multi-tenant plane: device-resident LoRA slots + the radix
        # prefix tree over the page pool.  Both are owned by the loop
        # thread like the allocator.
        from .adapter_pool import AdapterPool
        from .prefix_cache import RadixPrefixCache

        self.adapter_pool = AdapterPool(
            model_config, max_adapters=cfg.max_adapters,
            rank=cfg.lora_rank)
        self._cache: Optional[RadixPrefixCache] = (
            RadixPrefixCache(cfg.page_size) if cfg.prefix_cache else None)
        self._adapter_evictions_seen = 0
        # ONE device-resident PRNG key threads through every prefill and
        # decode call (each program splits and returns the successor):
        # host-side fold_in per step costs more than the decode math.
        # Sampling is therefore seeded per ENGINE, not per request.
        self._d_key = jax.random.PRNGKey(seed)
        b = cfg.batch_slots
        self.slots: List[Optional[_Request]] = [None] * b
        # Host mirrors are the rebuild source; the device copies below are
        # what decode consumes.  Admission/eviction/prefill mutate the
        # mirrors and mark them dirty; steady-state decode advances
        # tokens/lengths ON DEVICE and never re-uploads.
        self._page_tables = np.full((b, self.maxp), self.scratch, np.int32)
        self._seq_lens = np.zeros((b,), np.int32)
        self._tokens = np.zeros((b,), np.int32)
        self._active = np.zeros((b,), bool)
        self._temps = np.zeros((b,), np.float32)
        self._adapter_slots = np.full((b,), self.adapter_pool.zero_slot,
                                      np.int32)
        self._dirty = True
        self._d_tokens = self._d_page_tables = None
        self._d_seq_lens = self._d_active = self._d_temps = None
        self._d_adapter_slots = None
        self.step_count = 0
        self._req_counter = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        # Weighted-fair admission: one FIFO per tenant, picked by lowest
        # virtual finish time (classic WFQ — a tenant's vtime advances by
        # cost/weight per admitted request, clamped to the global vclock
        # so idle tenants can't bank unbounded credit).
        self._queues: Dict[str, List[_Request]] = {}
        self._vtime: Dict[str, float] = {}
        self._vclock = 0.0
        self._tenants: Dict[str, Dict[str, Any]] = {}
        # Control ops (adapter registration, cache clear) marshalled onto
        # the loop thread: it owns the pools the ops touch.
        self._control: List[Any] = []
        self._stop = False
        self.completed = 0
        self.shed = 0
        self.cancelled_count = 0
        # Instruments hoisted off the request path (registry lock).
        self._m_tokens = get_counter(
            "ray_tpu_gen_tokens_total",
            "Decoded tokens emitted by the inference engine")
        self._m_prefill = get_counter(
            "ray_tpu_gen_prefill_tokens_total",
            "Prompt tokens prefilled into the paged KV cache")
        self._m_pages = get_gauge(
            "ray_tpu_gen_kv_pages_in_use",
            "KV cache pages currently allocated to sequences",
            tag_keys=("pid",))
        self._m_queue = get_gauge(
            "ray_tpu_serve_engine_queue_depth",
            "Requests waiting for a batch slot", tag_keys=("pid",))
        self._m_active = get_gauge(
            "ray_tpu_serve_engine_active_seqs",
            "Sequences decoding in batch slots", tag_keys=("pid",))
        self._m_shed = get_counter(
            "ray_tpu_serve_engine_shed_total",
            "Requests rejected by admission control (overload)")
        self._m_completed = get_counter(
            "ray_tpu_serve_engine_completed_total",
            "Requests decoded to completion")
        self._m_cancelled = get_counter(
            "ray_tpu_serve_engine_cancelled_total",
            "Requests cancelled mid-flight (pages reclaimed)")
        self._m_ttft = get_histogram(
            "ray_tpu_serve_engine_ttft_seconds",
            "Submit-to-first-token latency",
            boundaries=(0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10))
        self._m_itl = get_histogram(
            "ray_tpu_serve_engine_itl_seconds",
            "Inter-token latency during decode",
            boundaries=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 1))
        self._m_pc_hits = get_counter(
            "ray_tpu_serve_prefix_cache_hits_total",
            "Prompts whose prefill reused cached KV prefix pages")
        self._m_pc_shared = get_gauge(
            "ray_tpu_serve_prefix_cache_pages_shared",
            "KV pages currently held by more than one owner",
            tag_keys=("pid",))
        self._m_adapter_evict = get_counter(
            "ray_tpu_serve_adapter_evictions_total",
            "LoRA adapters evicted from the device-resident pool")
        self._m_tenant_shed = get_counter(
            "ray_tpu_serve_tenant_shed_total",
            "Requests shed by weighted-fair admission, by tenant",
            tag_keys=("tenant",))
        self._m_stall = get_counter(
            "ray_tpu_engine_stall_seconds_total",
            "Decode-loop seconds spent stalled on admission prefills")
        # Recent TTFTs feeding the controller's SLO autoscaling signal.
        import collections

        self._ttft_recent = collections.deque(maxlen=cfg.ttft_window)
        import os

        self._pid_tags = {"pid": str(os.getpid())}
        # Flight recorder: engine identity + per-step deltas and the
        # recent step-wall / stall windows behind slo_signals jitter.
        self.engine_id = f"{os.getpid()}.{next(_ENGINE_SEQ)}"
        self._step_walls = collections.deque(maxlen=max(16, cfg.step_window))
        self._stall_events = collections.deque(
            maxlen=max(16, cfg.step_window))  # (wall_time, stall_s)
        self._pc_hits_total = 0
        self._evicted_total = 0
        # Device-memory attribution: the engine owns the big allocations,
        # so it names them for util/devmem snapshots.  Weights bytes are
        # static; pool/adapter lambdas chase the live arrays (donation
        # replaces them every step).
        from ..util import devmem

        self._weights_bytes = sum(
            int(getattr(x, "nbytes", 0))
            for x in jax.tree_util.tree_leaves(params))
        devmem.register_pool("model_weights", lambda: self._weights_bytes)
        devmem.register_pool("kv_pool", lambda: sum(
            int(a.nbytes) for a in self.pools.values()))
        devmem.register_pool("adapter_pool", lambda: sum(
            int(a.nbytes) for a in self.adapter_pool.arrays.values()))
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="llm-engine")
        self._thread.start()

    # ------------------------------------------------------------- client API

    def submit(self, prompt_tokens, max_new_tokens: int = 16,
               temperature: float = 0.0,
               stop_token: Optional[int] = None,
               adapter: Optional[str] = None,
               tenant: str = "default",
               weight: float = 1.0) -> TokenStream:
        """Queue one sequence; returns its token stream.

        ``adapter`` names a registered LoRA (None = base model);
        ``tenant``/``weight`` place the request in weighted-fair
        admission.  Overload sheds the HEAVIEST tenant's newest queued
        request with :class:`EngineOverloadedError` — when that is the
        submitter itself the error raises here, otherwise it lands on
        the victim's stream.  A light tenant is never shed by a heavy
        one's burst."""
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if prompt.size == 0 or prompt.size > self.config.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.size} outside (0, "
                f"{self.config.max_prompt_len}]")
        max_new = min(int(max_new_tokens), self.config.max_new_tokens_cap)
        if max_new <= 0:
            raise ValueError("max_new_tokens must be positive")
        if weight <= 0:
            raise ValueError("tenant weight must be positive")
        if adapter is not None and not self.adapter_pool.has(adapter):
            raise KeyError(f"adapter {adapter!r} is not registered")
        need = math.ceil((prompt.size + max_new) / self.config.page_size)
        if need > self.allocator.total:
            raise ValueError(
                f"request needs {need} KV pages but the pool holds only "
                f"{self.allocator.total} — raise EngineConfig.num_pages")
        with self._lock:
            if self._stop:
                raise RuntimeError("engine is shut down")
            self._req_counter += 1
            req = _Request(self._req_counter, prompt, max_new,
                           float(temperature), stop_token)
            req.tenant = tenant
            req.weight = float(weight)
            req.adapter = adapter
            rec = self._tenant_rec(tenant)
            rec["weight"] = float(weight)
            rec["submitted"] += 1
            # Capture the submitter's trace context (the replica's
            # execution span in the serve path): the loop thread emits
            # this request's queue/prefill/decode spans against it.
            from ..util import tracing

            req.trace_ctx = tracing.context_for_submit()
            req.submit_wall = time.time()
            self._queues.setdefault(tenant, []).append(req)
            victim: Optional[_Request] = None
            if self._queued_total() > self.config.max_queue:
                victim = self._shed_locked()
            self._m_queue.set(self._queued_total(), tags=self._pid_tags)
            self._wake.notify()
            if victim is req:
                raise EngineOverloadedError(
                    f"engine queue full ({self.config.max_queue} "
                    f"waiting); tenant {tenant!r} is the heaviest")
            if victim is not None:
                victim.finished = True
                victim.out_q.put((
                    "err", EngineOverloadedError(
                        f"shed by weighted-fair admission (tenant "
                        f"{victim.tenant!r} heaviest at overload)"),
                    self.step_count))
        return TokenStream(self, req)

    def _tenant_rec(self, tenant: str) -> Dict[str, Any]:
        rec = self._tenants.get(tenant)
        if rec is None:
            rec = self._tenants[tenant] = {
                "submitted": 0, "completed": 0, "shed": 0,
                "cancelled": 0, "weight": 1.0,
            }
        return rec

    def _queued_total(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @staticmethod
    def _req_cost(req: _Request) -> float:
        # Token work (prefill + worst-case decode) as the fair-share unit.
        return float(req.prompt.size + req.max_new)

    def _shed_locked(self) -> _Request:
        """Pick the victim: the tenant with the largest queued work per
        unit weight loses its NEWEST queued request (tail drop — oldest
        requests are closest to their SLO deadline)."""
        heaviest, load = None, -1.0
        for t, q in self._queues.items():
            if not q:
                continue
            w = max(self._tenants[t]["weight"], 1e-9)
            l = sum(self._req_cost(r) for r in q) / w
            if l > load:
                heaviest, load = t, l
        victim = self._queues[heaviest].pop()
        rec = self._tenants[heaviest]
        rec["shed"] += 1
        self.shed += 1
        self._m_shed.inc(1)
        self._m_tenant_shed.inc(1, tags={"tenant": heaviest})
        return victim

    def cancel(self, req: _Request) -> None:
        """Idempotent; a finished request is a no-op.  Pages return to
        the free list at the loop's next step boundary."""
        req.cancelled.set()
        with self._lock:
            self._wake.notify()

    def shutdown(self) -> None:
        with self._lock:
            self._stop = True
            self._wake.notify()
        self._thread.join(timeout=10)
        from ..util import devmem, steprec

        for name in ("model_weights", "kv_pool", "adapter_pool"):
            devmem.unregister_pool(name)
        steprec.dump_black_box(force=True)  # graceful exits get a fresh box

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            queued = self._queued_total()
            tenants = {
                t: dict(rec, queued=len(self._queues.get(t, [])))
                for t, rec in self._tenants.items()
            }
        active = sum(1 for s in self.slots if s is not None)
        from ..models.paged import trace_count

        return {
            "steps": self.step_count,
            "active_seqs": active,
            "queued": queued,
            "free_pages": self.allocator.free_count,
            "total_pages": self.allocator.total,
            "shared_pages": self.allocator.shared_count,
            "completed": self.completed,
            "shed": self.shed,
            "cancelled": self.cancelled_count,
            "decode_traces": trace_count("decode"),
            "prefill_traces": trace_count("prefill"),
            "prefill_prefix_traces": trace_count("prefill_prefix"),
            "mode": self.config.mode,
            "tenants": tenants,
            "prefix_cache": (self._cache.stats()
                             if self._cache is not None else None),
            "adapters": self.adapter_pool.stats(),
        }

    #: Window over which admission-stall seconds are summed for the
    #: autoscaler's stall-pressure signal.
    STALL_WINDOW_S = 30.0

    def slo_signals(self) -> Dict[str, Any]:
        """Queue-depth / TTFT snapshot for the controller's SLO-driven
        autoscaling (cheap: host counters plus a tiny sort), extended
        with the step ring's stall and jitter signals: seconds the decode
        loop spent stalled on admission prefills inside the last
        ``STALL_WINDOW_S``, and decode-step p99 jitter (p99 - p50 step
        wall).  The autoscaler reacts to stall pressure even while TTFT
        still holds — a saturated engine stalls before it breaches."""
        ttfts = sorted(self._ttft_recent)

        def pct(vals: List[float], p: float) -> float:
            if not vals:
                return 0.0
            return vals[min(len(vals) - 1, int(p * len(vals)))]

        with self._lock:
            queued = self._queued_total()
            tenant_queues = {t: len(q)
                             for t, q in self._queues.items() if q}
        now = time.time()
        stall_s = sum(s for (t, s) in list(self._stall_events)
                      if now - t <= self.STALL_WINDOW_S)
        walls = sorted(self._step_walls)
        p50, p99 = pct(walls, 0.50), pct(walls, 0.99)
        return {
            "queue_depth": queued,
            "active_seqs": sum(1 for s in self.slots if s is not None),
            "batch_slots": self.config.batch_slots,
            "ttft_p50_s": pct(ttfts, 0.50),
            "ttft_p90_s": pct(ttfts, 0.90),
            "ttft_count": len(ttfts),
            "completed": self.completed,
            "shed": self.shed,
            "stall_s_window": stall_s,
            "stall_window_s": self.STALL_WINDOW_S,
            "stall_frac": min(1.0, stall_s / self.STALL_WINDOW_S),
            "step_p50_s": p50,
            "step_p99_s": p99,
            "step_jitter_p99_s": max(0.0, p99 - p50),
            "tenant_queues": tenant_queues,
        }

    def _run_on_loop(self, fn, timeout: float = 30.0):
        """Run ``fn`` on the loop thread (it owns pools/cache/adapters)
        and return its result.  Raises what ``fn`` raised."""
        done = threading.Event()
        box: Dict[str, Any] = {}

        def task():
            try:
                box["r"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["e"] = e
            finally:
                done.set()

        with self._lock:
            if self._stop:
                raise RuntimeError("engine is shut down")
            self._control.append(task)
            self._wake.notify()
        if not done.wait(timeout):
            raise TimeoutError("engine loop did not run control op")
        if "e" in box:
            raise box["e"]
        return box.get("r")

    def register_adapter(self, name: str, source: Any) -> None:
        """Register (or replace) a LoRA adapter.  Replacement drops any
        resident copy AND the adapter's prefix-cache tree — its cached V
        deltas are stale."""

        def do():
            self.adapter_pool.register(name, source)
            if self._cache is not None:
                self._cache.drop_adapter(name, self.allocator)

        self._run_on_loop(do)

    def clear_prefix_cache(self) -> int:
        """Release every cache-held page ref (tests/bench drain to a
        balanced free list; compiled programs stay warm)."""
        if self._cache is None:
            return 0
        return self._run_on_loop(
            lambda: self._cache.clear(self.allocator))

    def warmup(self) -> None:
        """Compile the decode program and every prefill bucket up front
        (one dummy sequence per bucket) so serving traffic never pays a
        trace."""
        # A fresh engine's warmup is a legitimate compile phase: stand
        # the sentinel down while it traces (a previous engine in this
        # process may have armed with different geometry), re-arm below.
        from ..devtools import jitguard
        jitguard.disarm()
        # max_new_tokens=2: the first token comes from PREFILL — the
        # decode program only compiles once a second token is needed.
        probe = self.submit([1], max_new_tokens=2)
        for _ in probe:
            pass
        for bucket in self.config.prefill_buckets()[1:]:
            n = min(bucket, self.config.max_prompt_len)
            if self._cache is not None:
                # The previous bucket's ones-prompt cached its pages; a
                # hit here would route to the suffix path and skip the
                # cold prefill compile this bucket exists to pay.
                self.clear_prefix_cache()
            s = self.submit(np.ones((n,), np.int32), max_new_tokens=1)
            for _ in s:
                pass
        if self._cache is not None \
                and self.config.max_prompt_len >= self.config.page_size:
            # Re-run the largest prompt: it hits the pages the line above
            # cached, compiling the COW copy + suffix-prefill path too.
            n = self.config.max_prompt_len
            for _ in self.submit(np.ones((n,), np.int32),
                                 max_new_tokens=1):
                pass
            self.clear_prefix_cache()
            # The re-run traces the prefix path only for the ONE suffix
            # bucket (and COW divergence) its geometry happens to hit —
            # compile every suffix bucket and the COW copy explicitly
            # (dummy tokens into the scratch page; page 0 onto itself)
            # so no real prefix hit after warmup pays a trace.
            def _warm_prefix_path():
                import jax.numpy as jnp

                from ..models.paged import copy_page, paged_prefill_prefix
                adapters = self.adapter_pool.arrays
                pt = jnp.full((self.maxp,), self.scratch, jnp.int32)
                zero = jnp.asarray(0, jnp.int32)
                temp = jnp.asarray(0.0, jnp.float32)
                for b in self.config.prefill_buckets():
                    _, self._d_key, self.pools = paged_prefill_prefix(
                        self.model_config, self.params, self.pools,
                        adapters, jnp.zeros((1, b), jnp.int32), zero,
                        jnp.asarray(1, jnp.int32), pt, zero, temp,
                        self._d_key)
                self.pools = copy_page(self.pools, zero, zero)
            self._run_on_loop(_warm_prefix_path)
        # Compile the adapter-load path too (zero payload into the zero
        # slot): the first real LoRA registration after warmup must be an
        # execution, not a fresh trace.
        self._run_on_loop(self.adapter_pool.warmup_compile)
        # Recompile sentinel (RT_DEBUG_JIT=1): freeze every program's
        # trace count — decode, each prefill bucket, the COW/suffix path,
        # adapter loads — so any post-warmup trace raises RecompileError
        # at the stray call site instead of silently paying a compile in
        # the step loop.  No-op when the env flag is off.
        jitguard.arm()

    # ---------------------------------------------------------------- loop

    def _bucket_len(self, n: int) -> int:
        for b in self.config.prefill_buckets():
            if b >= n:
                return b
        return self.config.prefill_buckets()[-1]

    def _pick_tenant_locked(self) -> Optional[str]:
        """Lowest-virtual-time tenant with queued work (WFQ pick)."""
        best, best_v = None, None
        for t, q in self._queues.items():
            if not q:
                continue
            v = max(self._vtime.get(t, 0.0), self._vclock)
            if best_v is None or v < best_v:
                best, best_v = t, v
        return best

    def _admit_locked(self) -> List[_Request]:
        """Move queued requests into free slots (called under the lock).
        Continuous mode admits whenever a slot AND pages are free;
        whole-request mode admits a full gang only into an EMPTY batch.
        Tenants are drained in weighted-fair order; each admission pins
        its prefix-cache match (refcounted shares) and allocates only the
        pages the cache can't cover, evicting cold cache leaves first
        when the pool runs dry."""
        admitted: List[_Request] = []
        whole = self.config.mode == "whole_request"
        if whole and any(s is not None for s in self.slots):
            return admitted
        for slot in range(self.config.batch_slots):
            if self.slots[slot] is not None:
                continue
            tenant = self._pick_tenant_locked()
            if tenant is None:
                continue
            req = self._queues[tenant][0]
            if not self.adapter_pool.can_acquire(req.adapter):
                break  # every adapter slot pinned: wait for an eviction
            need_total = math.ceil((req.prompt.size + req.max_new)
                                   / self.config.page_size)
            match = None
            shared: List[int] = []
            if self._cache is not None:
                match = self._cache.lookup(req.adapter, req.prompt)
                shared = match.pages
                # Pin the match BEFORE any cache eviction below can free
                # the very pages it names.
                self._cache.claim(match, self.allocator)  # rt-owns: prefix_claim
            need = need_total - len(shared)
            pages = self.allocator.alloc(need)
            if pages is None and self._cache is not None:
                deficit = need - self.allocator.free_count
                if self._cache.evict_leaves(deficit, self.allocator):
                    pages = self.allocator.alloc(need)
            if pages is None:
                if match is not None:  # roll the claim back
                    held = list(shared)
                    if match.cow_src is not None:
                        held.append(match.cow_src)
                    if held:
                        self.allocator.free(held)
                break  # pool pressure: leave queued, retry next step
            self._queues[tenant].pop(0)
            # Reserve (pin) the adapter slot NOW, host-only: requests
            # admitted in this same round must see each other's pins, or
            # a wave of distinct adapters could over-commit the slots the
            # can_acquire check saw free.  Weights load at prefill.
            req.adapter_slot = self.adapter_pool.reserve(req.adapter)
            v_start = max(self._vtime.get(tenant, 0.0), self._vclock)
            w = max(req.weight, 1e-9)
            self._vtime[tenant] = v_start + self._req_cost(req) / w
            self._vclock = v_start
            req.admit_wall = time.time()
            req.pages = shared + pages
            req.match = match
            if match is not None and match.cow_src is not None:
                req.cow_ref = match.cow_src
            req.cache_hit_len = match.prefix_len if match else 0
            pt = np.full((self.maxp,), self.scratch, np.int32)
            pt[:need_total] = req.pages
            req.page_table = pt
            req.slot = slot
            self.slots[slot] = req
            admitted.append(req)
        if admitted:
            self._m_queue.set(self._queued_total(), tags=self._pid_tags)
        return admitted

    def _emit_req_span(self, req: _Request, name: str, start: float,
                       end: float, **attrs) -> None:
        """One request-stage span (queue / prefill / decode), parented to
        the submitter's context.  Buffered emission (util/tracing ring) —
        the decode loop never pays a head RPC for tracing."""
        if req.trace_ctx is None or start <= 0:
            return
        from ..util import tracing

        tracing.emit_span(
            tracing.make_span(req.trace_ctx, name, start, end, **attrs))

    def _evict(self, slot: int, reason: str) -> None:
        req = self.slots[slot]
        assert req is not None
        # Decode-lifetime span: first token -> eviction.  Token count,
        # TTFT, and mean ITL ride as attrs so per-request latency
        # attribution is derivable from the span tree alone.
        now_wall = time.time()
        self._emit_req_span(
            req, "engine:decode", req.first_wall or req.admit_wall,
            now_wall, tokens=req.generated, reason=reason,
            ttft_s=round(req.first_token_t - req.submit_t, 6)
            if req.first_token_t is not None else None,
            mean_itl_s=round(sum(req.itls) / len(req.itls), 6)
            if req.itls else None)
        self.allocator.free(req.pages)  # refcounted: shared prefix
        req.pages = []                  # pages may stay cached
        if req.cow_ref is not None:     # evicted before the COW copy ran
            self.allocator.free([req.cow_ref])
            req.cow_ref = None
        if req.adapter_slot >= 0:
            self.adapter_pool.release(req.adapter)
            req.adapter_slot = -1
        req.finished = True
        self._evicted_total += 1
        self.slots[slot] = None
        self._page_tables[slot, :] = self.scratch
        self._seq_lens[slot] = 0
        self._tokens[slot] = 0
        self._active[slot] = False
        self._temps[slot] = 0.0
        self._adapter_slots[slot] = self.adapter_pool.zero_slot
        self._dirty = True
        rec = self._tenant_rec(req.tenant)
        if reason == "cancelled":
            self.cancelled_count += 1
            rec["cancelled"] += 1
            self._m_cancelled.inc(1)
        elif reason in ("complete", "stop"):
            self.completed += 1
            rec["completed"] += 1
            self._m_completed.inc(1)
        if reason == "shutdown":
            # Loudly: a truncated generation must not look complete.
            req.out_q.put(("err", RuntimeError(
                "engine shut down mid-generation"), self.step_count))
        else:
            req.out_q.put(("done", reason, self.step_count))

    def _prefill(self, req: _Request) -> None:
        """Run one admitted sequence's prompt through the bucketed
        prefill program and emit its first token (TTFT point).  A
        prefix-cache hit copies the COW page (mid-page divergence) and
        prefills only the uncached suffix."""
        import jax.numpy as jnp

        from ..models.paged import (copy_page, paged_prefill,
                                    paged_prefill_prefix)

        # Admission reserved (pinned) the slot; materialize the weights
        # if this is the adapter's first use since eviction.
        self.adapter_pool.ensure_loaded(req.adapter)
        ev = self.adapter_pool.evictions
        if ev > self._adapter_evictions_seen:
            self._m_adapter_evict.inc(ev - self._adapter_evictions_seen)
            self._adapter_evictions_seen = ev
        n = req.prompt.size
        # Queue-wait span (submit -> admission into a batch slot).
        self._emit_req_span(req, "engine:queue", req.submit_wall,
                            req.admit_wall or req.submit_wall,
                            prompt_len=int(n))
        pf_start = time.time()
        prefix_len = req.cache_hit_len
        aid = jnp.asarray(req.adapter_slot, jnp.int32)
        adapters = self.adapter_pool.arrays
        if prefix_len > 0:
            match = req.match
            if match.cow_src is not None:
                # Private copy of the divergent page, then drop the
                # claim's extra ref on the source.
                dest = int(req.page_table[len(match.pages)])
                self.pools = copy_page(
                    self.pools, jnp.asarray(match.cow_src, jnp.int32),
                    jnp.asarray(dest, jnp.int32))
                self.allocator.free([req.cow_ref])
                req.cow_ref = None
            suffix = req.prompt[prefix_len:]
            s_pad = self._bucket_len(suffix.size)
            req.prefill_bucket = s_pad
            toks = np.zeros((1, s_pad), np.int32)
            toks[0, :suffix.size] = suffix
            first, self._d_key, self.pools = paged_prefill_prefix(
                self.model_config, self.params, self.pools, adapters,
                jnp.asarray(toks), jnp.asarray(prefix_len, jnp.int32),
                jnp.asarray(n, jnp.int32), jnp.asarray(req.page_table),
                aid, jnp.asarray(req.temperature, jnp.float32),
                self._d_key)
            self._m_pc_hits.inc(1)
            self._pc_hits_total += 1
            self._m_prefill.inc(suffix.size)  # only the work actually done
        else:
            s_pad = self._bucket_len(n)
            req.prefill_bucket = s_pad
            toks = np.zeros((1, s_pad), np.int32)
            toks[0, :n] = req.prompt
            first, self._d_key, self.pools = paged_prefill(
                self.model_config, self.params, self.pools, adapters,
                jnp.asarray(toks), jnp.asarray(n, jnp.int32),
                jnp.asarray(req.page_table), aid,
                jnp.asarray(req.temperature, jnp.float32), self._d_key)
            self._m_prefill.inc(n)
        first = int(first)  # rt-sync-ok: THE prefill readback — the first token must reach the host to stream it
        # Cache every fully-frozen prompt page (decode appends past the
        # prompt, so pages wholly inside it never change again).
        if self._cache is not None:
            full = n // self.config.page_size
            if full > 0:
                self._cache.insert(
                    req.adapter, req.prompt[:full * self.config.page_size],
                    [int(p) for p in req.page_table[:full]],
                    self.allocator)
            self._m_pc_shared.set(self.allocator.shared_count,
                                  tags=self._pid_tags)
        now = time.perf_counter()
        req.length = n
        req.first_token_t = now
        req.last_token_t = now
        req.first_wall = time.time()
        # Prefill span: bucket + cached-prefix attrs make padding waste
        # and cache effectiveness readable straight off the trace.
        self._emit_req_span(req, "engine:prefill", pf_start, req.first_wall,
                            bucket=int(req.prefill_bucket),
                            prompt_len=int(n),
                            cached_prefix=int(prefix_len))
        ttft = now - req.submit_t
        self._m_ttft.observe(ttft)
        self._ttft_recent.append(ttft)
        slot = req.slot
        self._page_tables[slot] = req.page_table
        self._seq_lens[slot] = n
        self._tokens[slot] = first
        self._active[slot] = True
        self._temps[slot] = req.temperature
        self._adapter_slots[slot] = req.adapter_slot
        self._dirty = True
        self._emit_token(req, first)

    def _emit_token(self, req: _Request, token: int) -> None:
        req.generated += 1
        self._m_tokens.inc(1)
        req.out_q.put(("tok", token, self.step_count))
        if req.stop_token is not None and token == req.stop_token:
            self._evict(req.slot, "stop")
        elif req.generated >= req.max_new:
            self._evict(req.slot, "complete")

    def _fail_inflight(self, exc: BaseException) -> None:
        """A model-call failure must not kill the loop thread silently:
        every in-flight request gets the error on its stream, pages
        return to the free list, and the pools are rebuilt (a failed
        donated call may have invalidated them).  Queued requests stay
        queued — they retry against the fresh pool."""
        from ..models.paged import init_paged_pools

        now_wall = time.time()
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            self._emit_req_span(
                req, "engine:decode",
                req.first_wall or req.admit_wall or req.submit_wall,
                now_wall, tokens=req.generated, reason="error",
                error=repr(exc)[:200])
            self.allocator.free(req.pages)
            req.pages = []
            if req.cow_ref is not None:
                self.allocator.free([req.cow_ref])
                req.cow_ref = None
            if req.adapter_slot >= 0:
                self.adapter_pool.release(req.adapter)
                req.adapter_slot = -1
            req.finished = True
            self.slots[slot] = None
            req.out_q.put(("err", exc, self.step_count))
        # The pools are rebuilt below, so every cached KV page and every
        # resident adapter slot is garbage: drop the tree's refs and
        # reset the adapter pool (the registry survives; adapters reload
        # on next acquire).
        if self._cache is not None:
            self._cache.clear(self.allocator)
        self.adapter_pool.reset()
        self._page_tables[:] = self.scratch
        self._seq_lens[:] = 0
        self._tokens[:] = 0
        self._active[:] = False
        self._temps[:] = 0.0
        self._adapter_slots[:] = self.adapter_pool.zero_slot
        self._dirty = True
        self.pools = init_paged_pools(
            self.model_config, self.config.pool_pages,
            self.config.page_size)

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    break
                control, self._control = self._control, []
                # Reap cancellations first: queued cancels just drop,
                # in-flight cancels free pages before admission looks at
                # the pool.
                reaped = False
                for q in self._queues.values():
                    keep = []
                    for r in q:
                        if r.cancelled.is_set():
                            self.cancelled_count += 1
                            self._tenant_rec(r.tenant)["cancelled"] += 1
                            self._m_cancelled.inc(1)
                            r.out_q.put(
                                ("done", "cancelled", self.step_count))
                            reaped = True
                        else:
                            keep.append(r)
                    q[:] = keep
                if reaped:
                    self._m_queue.set(self._queued_total(),
                                      tags=self._pid_tags)
                for slot, req in enumerate(self.slots):
                    if req is not None and req.cancelled.is_set():
                        self._evict(slot, "cancelled")
                admitted = self._admit_locked()
                active = sum(1 for s in self.slots if s is not None)
                if not admitted and active == 0 and not control:
                    self._m_active.set(0, tags=self._pid_tags)
                    self._m_pages.set(self.allocator.used_count,
                                      tags=self._pid_tags)
                    self._wake.wait(timeout=0.05)
                    continue
            # Model work runs OUTSIDE the lock: pools/slot arrays belong
            # to this thread; submit() only appends to the wait queue.
            # Control ops (adapter registration, cache clear) run here
            # for the same reason.
            for task in control:
                task()
            try:
                self._run_step(admitted)
            except Exception as e:  # noqa: BLE001 — fail streams, not
                self._fail_inflight(e)  # the loop thread
        # Shutdown: fail queued + in-flight requests loudly, and unblock
        # any control-op waiters.
        with self._lock:
            pending = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
            control, self._control = self._control, []
            self._m_queue.set(0, tags=self._pid_tags)
        for task in control:
            task()
        for req in pending:
            req.out_q.put(("err", RuntimeError(
                "engine shut down before admission"), self.step_count))
        for slot, req in enumerate(self.slots):
            if req is not None:
                self._evict(slot, "shutdown")

    def _run_step(self, admitted: List[_Request]) -> None:
        import jax.numpy as jnp

        from ..models.paged import paged_decode_step, trace_counts

        # Flight recorder entry state: step wall, admission-stall span,
        # and per-step deltas come from host counters only — no device
        # sync, no lock beyond what the loop already holds.
        rec_on = self.config.step_record
        t0 = time.perf_counter()
        stall_s = 0.0
        evicted0 = self._evicted_total
        shed0 = self.shed
        pc_hits0 = self._pc_hits_total
        traces0 = trace_counts() if rec_on else None
        for req in admitted:
            pf0 = time.perf_counter()
            self._prefill(req)
            stall_s += time.perf_counter() - pf0
        if not any(s is not None for s in self.slots):
            if rec_on and admitted:
                self._record_step(t0, stall_s, len(admitted), evicted0,
                                  shed0, pc_hits0, traces0, decoded=False)
            return
        self.step_count += 1
        if self._dirty:
            # Membership changed since the last step: re-upload the
            # host mirrors.  Steady-state decode skips this — tokens,
            # lengths, and the PRNG key advance on device.
            self._d_tokens = jnp.asarray(self._tokens)
            self._d_page_tables = jnp.asarray(self._page_tables)
            self._d_seq_lens = jnp.asarray(self._seq_lens)
            self._d_active = jnp.asarray(self._active)
            self._d_temps = jnp.asarray(self._temps)
            self._d_adapter_slots = jnp.asarray(self._adapter_slots)
            self._dirty = False
        (self._d_tokens, self._d_seq_lens, self._d_key,
         self.pools) = paged_decode_step(
            self.model_config, self.params, self.pools,
            self.adapter_pool.arrays,
            self._d_tokens, self._d_page_tables, self._d_seq_lens,
            self._d_active, self._d_temps, self._d_adapter_slots,
            self._d_key)
        toks = np.asarray(self._d_tokens)  # rt-sync-ok: THE decode-step readback — one batched token fetch per step
        now = time.perf_counter()
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            self._seq_lens[slot] += 1
            req.length += 1
            self._tokens[slot] = toks[slot]
            if req.last_token_t is not None:
                itl = now - req.last_token_t
                req.itls.append(itl)
                self._m_itl.observe(itl)
            req.last_token_t = now
            self._emit_token(req, int(toks[slot]))
        self._m_active.set(
            sum(1 for s in self.slots if s is not None),
            tags=self._pid_tags)
        self._m_pages.set(self.allocator.used_count,
                          tags=self._pid_tags)
        if rec_on:
            self._record_step(t0, stall_s, len(admitted), evicted0,
                              shed0, pc_hits0, traces0, decoded=True)

    def _record_step(self, t0: float, stall_s: float, admitted: int,
                     evicted0: int, shed0: int, pc_hits0: int,
                     traces0: Optional[Dict[str, int]],
                     decoded: bool) -> None:
        """Append one flight-recorder record for the step that just ran.
        Called on the loop thread; everything here is host bookkeeping
        (the decode result was already synced for token emission)."""
        from ..models.paged import trace_counts
        from ..util import devmem, steprec

        wall_s = time.perf_counter() - t0
        now = time.time()
        if decoded:
            self._step_walls.append(wall_s)
        if stall_s > 0:
            self._stall_events.append((now, stall_s))
            self._m_stall.inc(stall_s)
        # Compile observability: a trace-count bump inside this step means
        # this step's wall paid the compile — attribute it by program.
        if traces0 is not None:
            traces1 = trace_counts()
            for prog, n in traces1.items():
                if n > traces0.get(prog, 0):
                    devmem.record_compile(prog, wall_s)
        with self._lock:
            queued = self._queued_total()
            tenants = {t: len(q) for t, q in self._queues.items() if q}
        steprec.record_step({
            "t": round(now, 3),
            "engine": self.engine_id,
            "step": self.step_count,
            "wall_s": round(wall_s, 6),
            "stall_s": round(stall_s, 6),
            "occupancy": sum(1 for s in self.slots if s is not None),
            "slots": self.config.batch_slots,
            "admitted": admitted,
            "evicted": self._evicted_total - evicted0,
            "shed": self.shed - shed0,
            "queued": queued,
            "pages_used": self.allocator.used_count,
            "pages_free": self.allocator.free_count,
            "pages_shared": self.allocator.shared_count,
            "prefix_hits": self._pc_hits_total - pc_hits0,
            "adapter_pins": self.adapter_pool.pinned_count,
            "tenants": tenants,
        })


# ------------------------------------------------------------ serve binding


_MODEL_BUILDERS = {
    "tiny": lambda: _tiny_config(),
    "b1": lambda: _b1_config(),
}


def _tiny_config():
    import jax.numpy as jnp

    from ..models import LlamaConfig

    return LlamaConfig.tiny(remat=False, dtype=jnp.float32)


def _b1_config():
    import jax.numpy as jnp

    from ..models import LlamaConfig

    return LlamaConfig.b1(remat=False, dtype=jnp.bfloat16)


def random_lora(model_config, seed: int, rank: int = 8,
                alpha: float = 16.0):
    """A deterministic nonzero LoRA for tests/bench/demo adapters
    (``lora_init`` zeroes the B matrices, which would make every adapter
    a no-op; serving wants adapters that visibly change the logits)."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import lora_init

    lora = lora_init(model_config, jax.random.PRNGKey(seed), rank=rank,
                     alpha=alpha)
    base = jax.random.PRNGKey(seed ^ 0x5BD1)
    for i, layer in enumerate(lora["layers"]):
        kq, kv = jax.random.split(jax.random.fold_in(base, i))
        layer["wq_lora_b"] = (
            jax.random.normal(kq, layer["wq_lora_b"].shape, jnp.float32)
            * 0.05).astype(layer["wq_lora_b"].dtype)
        layer["wv_lora_b"] = (
            jax.random.normal(kv, layer["wv_lora_b"].shape, jnp.float32)
            * 0.05).astype(layer["wv_lora_b"].dtype)
    return lora


class LLMServer:
    """The deployment callable: one engine per replica, tokens streamed
    through serve's per-item streaming path (handle iterators, HTTP SSE,
    gRPC server-streaming).  A consumer that disconnects mid-stream
    closes the generator, which cancels the request and frees its pages.

    Multi-tenant: ``adapter=`` picks a registered LoRA (defaulting to the
    ambient multiplexed model id, so ``multiplexed_model_id`` routing
    composes with the engine's batched adapters), ``tenant``/``weight``
    feed weighted-fair admission."""

    def __init__(self, model: str = "tiny",
                 engine: Optional[dict] = None, seed: int = 0,
                 warmup: bool = False,
                 adapters: Optional[Dict[str, Any]] = None):
        import jax

        from ..models import llama_init

        cfg = _MODEL_BUILDERS[model]()
        params = llama_init(cfg, jax.random.PRNGKey(seed))
        self.engine = InferenceEngine(
            cfg, params, EngineConfig(**(engine or {})), seed=seed)
        for name, spec in (adapters or {}).items():
            self.load_adapter(name, spec)
        if warmup:
            self.engine.warmup()

    def load_adapter(self, name: str, source: Any = None) -> str:
        """Register a LoRA adapter on this replica's engine.  ``source``
        is packed arrays / a lora pytree / an object-plane ref / a
        zero-arg builder, or an int seed (a deterministic random adapter
        — handy for tests and bench)."""
        if isinstance(source, int):
            seed = source
            cfg = self.engine.model_config
            rank = self.engine.config.lora_rank
            source = lambda: random_lora(cfg, seed, rank=rank)  # noqa: E731
        self.engine.register_adapter(name, source)
        return name

    def __call__(self, prompt_tokens, max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 stop_token: Optional[int] = None,
                 adapter: Optional[str] = None,
                 tenant: str = "default", weight: float = 1.0):
        if adapter is None:
            # serve.multiplexed routing: the handle's multiplexed_model_id
            # arrives via the replica's contextvar.
            from .multiplex import get_multiplexed_model_id

            adapter = get_multiplexed_model_id() or None
        stream = self.engine.submit(
            prompt_tokens, max_new_tokens=max_new_tokens,
            temperature=temperature, stop_token=stop_token,
            adapter=adapter, tenant=tenant, weight=weight)
        try:
            for tok in stream:
                yield tok
        finally:
            # Reached on completion AND on GeneratorExit (client gone,
            # task cancelled): idempotent, frees pages mid-flight.
            stream.cancel()

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()

    def engine_metrics(self) -> Dict[str, Any]:
        """SLO signal snapshot for the controller's autoscaler."""
        return self.engine.slo_signals()


def llm_app(model: str = "tiny", engine: Optional[dict] = None,
            num_replicas: int = 1, name: str = "llm", seed: int = 0,
            warmup: bool = False,
            adapters: Optional[Dict[str, Any]] = None):
    """Build a servable LLM application:
    ``serve.run(llm_app(...))`` then stream tokens via
    ``handle.options(stream=True).remote([1, 2, 3], 16)`` or POST with
    ``Accept: text/event-stream``.  ``adapters`` maps adapter name to an
    int seed (random adapter) or weight source, registered on every
    replica at startup."""
    from .api import Deployment

    dep = Deployment(LLMServer, name, num_replicas=num_replicas)
    return dep.bind(model=model, engine=engine, seed=seed, warmup=warmup,
                    adapters=adapters)
