"""Continuous-batching LLM inference engine behind serve.

Role-equivalent to the Ray Serve LLM stack's engine loop (reference: Ray
Serve's LLM deployments wrap a continuous-batching engine; PAPER.md L7
names model multiplexing + streaming as the serve capability surface).
The engine turns a replica from a request router into an inference loop:

- ONE decode program (``models/paged.py``) serves every admission mix —
  batch slots, page tables, and lengths are data, so after warmup the
  loop never recompiles.
- Queued sequences are admitted into free batch slots BETWEEN decode
  steps; a prefill runs as its own (bucketed) program, so running
  sequences stall by at most one step per admission.
- Finished/cancelled sequences are evicted between steps and their pages
  return to the free list; the page pool's worst-case footprint is
  reserved at admission, so decode can never die of page exhaustion
  mid-flight.
- Admission control sheds with a typed :class:`EngineOverloadedError`
  when the wait queue exceeds its bound — goodput holds under overload
  instead of collapsing into unbounded queueing.
- Tokens stream out per-request as they decode (the deployment's sync
  generator feeds serve's existing per-item streaming path: handles,
  HTTP SSE, gRPC server-streaming); a consumer that disappears cancels
  the request and frees its pages mid-flight.

``mode="whole_request"`` keeps the same kernels but only admits when the
batch is EMPTY (gang admission, drain to completion) — the baseline
``bench_serve.py`` compares against.
"""

from __future__ import annotations

import dataclasses
import math
import queue as _queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class EngineOverloadedError(Exception):
    """Typed admission-control shed: the engine's wait queue is full.

    Callers see this at submit time (the request never held pages or a
    slot); clients should back off and retry — the standard overload
    contract (reference: Serve's backpressure returns 503)."""


@dataclasses.dataclass
class EngineConfig:
    """Sizing knobs for one replica's engine.

    ``page_table_width`` (MAXP) and the pool size derive from the prompt
    and output caps so admission's worst-case reservation always fits a
    fresh pool: ``num_pages = 0`` auto-sizes to ``batch_slots`` times the
    per-sequence worst case."""

    batch_slots: int = 8
    page_size: int = 16
    max_prompt_len: int = 64
    max_new_tokens_cap: int = 128
    num_pages: int = 0            # 0 -> batch_slots * pages_per_seq
    max_queue: int = 32           # admission bound: beyond this, shed
    mode: str = "continuous"      # or "whole_request" (gang admission)
    stream_timeout_s: float = 120.0

    @property
    def pages_per_seq(self) -> int:
        # The page table must cover BOTH the worst-case sequence AND the
        # largest prefill bucket: padded prefill positions index the
        # table, and jit clamps an out-of-range gather to the last entry
        # — which would silently corrupt a real page.
        worst = math.ceil(
            (self.max_prompt_len + self.max_new_tokens_cap)
            / self.page_size)
        return max(worst, self.prefill_buckets()[-1] // self.page_size)

    @property
    def pool_pages(self) -> int:
        return self.num_pages or self.batch_slots * self.pages_per_seq

    def prefill_buckets(self) -> List[int]:
        """Padded prompt lengths (one compile each): page-size multiples
        doubling up to the prompt cap."""
        out, b = [], self.page_size
        while b < self.max_prompt_len:
            out.append(b)
            b *= 2
        out.append(max(b, self.max_prompt_len))
        return out


class _Request:
    __slots__ = (
        "req_id", "prompt", "max_new", "temperature", "stop_token",
        "out_q", "cancelled", "finished", "pages", "page_table",
        "length", "generated", "submit_t", "first_token_t",
        "last_token_t", "itls", "slot",
        "trace_ctx", "submit_wall", "admit_wall", "first_wall",
        "prefill_bucket",
    )

    def __init__(self, req_id: int, prompt: np.ndarray, max_new: int,
                 temperature: float, stop_token: Optional[int]):
        self.req_id = req_id
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.stop_token = stop_token
        self.out_q: "_queue.Queue" = _queue.Queue()
        self.cancelled = threading.Event()
        self.finished = False
        self.pages: List[int] = []
        self.page_table: Optional[np.ndarray] = None
        self.length = 0
        self.generated = 0
        self.submit_t = time.perf_counter()
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        # Engine-side inter-token latencies: measured at emission, so
        # they reflect decode cadence, not consumer scheduling.
        self.itls: List[float] = []
        self.slot = -1
        # Tracing: the submitter's span context (None when the request
        # arrived untraced/unsampled — then the engine emits nothing) plus
        # wall-clock transition stamps for the queue/prefill/decode spans
        # (submit_t/first_token_t are perf_counter and can't be shared
        # with wall-clocked spans from other processes).
        self.trace_ctx: Optional[Dict[str, str]] = None
        self.submit_wall = 0.0
        self.admit_wall = 0.0
        self.first_wall = 0.0
        self.prefill_bucket = 0


class TokenStream:
    """Per-request token iterator; the consumer side of the engine's
    emission queue.  ``cancel()`` (or closing the iterating generator)
    releases the request's slot and pages at the next step boundary."""

    def __init__(self, engine: "InferenceEngine", req: _Request):
        self._engine = engine
        self._req = req
        self.steps: List[int] = []   # decode-step index of each token
        self.ttft_s: Optional[float] = None

    def __iter__(self):
        return self

    def __next__(self) -> int:
        try:
            kind, payload, step = self._req.out_q.get(
                timeout=self._engine.config.stream_timeout_s)
        except _queue.Empty:
            self.cancel()
            raise RuntimeError(
                "engine stream stalled past stream_timeout_s") from None
        if kind == "tok":
            if self.ttft_s is None and self._req.first_token_t is not None:
                self.ttft_s = self._req.first_token_t - self._req.submit_t
            self.steps.append(step)
            return int(payload)
        if kind == "err":
            raise payload
        raise StopIteration  # ("done", reason)

    def cancel(self) -> None:
        self._engine.cancel(self._req)


class InferenceEngine:
    """One replica's decode loop: host-side sequence/slot state machine
    around the jitted paged programs.  The loop runs on a dedicated
    daemon thread; ``submit()`` is called from any number of request
    threads and only touches the wait queue under the lock — pools,
    allocator, and slot arrays belong to the loop thread alone."""

    def __init__(self, model_config, params, config: EngineConfig,
                 seed: int = 0):
        import jax

        from ..models.paged import (PageAllocator, init_paged_pools)
        from ..util.metrics import get_counter, get_gauge, get_histogram

        self.model_config = model_config
        self.params = params
        self.config = config
        cfg = config
        self.maxp = cfg.pages_per_seq
        self.scratch = cfg.pool_pages  # scratch page index
        self.pools = init_paged_pools(model_config, cfg.pool_pages,
                                      cfg.page_size)
        self.allocator = PageAllocator(cfg.pool_pages)
        # ONE device-resident PRNG key threads through every prefill and
        # decode call (each program splits and returns the successor):
        # host-side fold_in per step costs more than the decode math.
        # Sampling is therefore seeded per ENGINE, not per request.
        self._d_key = jax.random.PRNGKey(seed)
        b = cfg.batch_slots
        self.slots: List[Optional[_Request]] = [None] * b
        # Host mirrors are the rebuild source; the device copies below are
        # what decode consumes.  Admission/eviction/prefill mutate the
        # mirrors and mark them dirty; steady-state decode advances
        # tokens/lengths ON DEVICE and never re-uploads.
        self._page_tables = np.full((b, self.maxp), self.scratch, np.int32)
        self._seq_lens = np.zeros((b,), np.int32)
        self._tokens = np.zeros((b,), np.int32)
        self._active = np.zeros((b,), bool)
        self._temps = np.zeros((b,), np.float32)
        self._dirty = True
        self._d_tokens = self._d_page_tables = None
        self._d_seq_lens = self._d_active = self._d_temps = None
        self.step_count = 0
        self._req_counter = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._pending: List[_Request] = []
        self._stop = False
        self.completed = 0
        self.shed = 0
        self.cancelled_count = 0
        # Instruments hoisted off the request path (registry lock).
        self._m_tokens = get_counter(
            "ray_tpu_gen_tokens_total",
            "Decoded tokens emitted by the inference engine")
        self._m_prefill = get_counter(
            "ray_tpu_gen_prefill_tokens_total",
            "Prompt tokens prefilled into the paged KV cache")
        self._m_pages = get_gauge(
            "ray_tpu_gen_kv_pages_in_use",
            "KV cache pages currently allocated to sequences",
            tag_keys=("pid",))
        self._m_queue = get_gauge(
            "ray_tpu_serve_engine_queue_depth",
            "Requests waiting for a batch slot", tag_keys=("pid",))
        self._m_active = get_gauge(
            "ray_tpu_serve_engine_active_seqs",
            "Sequences decoding in batch slots", tag_keys=("pid",))
        self._m_shed = get_counter(
            "ray_tpu_serve_engine_shed_total",
            "Requests rejected by admission control (overload)")
        self._m_completed = get_counter(
            "ray_tpu_serve_engine_completed_total",
            "Requests decoded to completion")
        self._m_cancelled = get_counter(
            "ray_tpu_serve_engine_cancelled_total",
            "Requests cancelled mid-flight (pages reclaimed)")
        self._m_ttft = get_histogram(
            "ray_tpu_serve_engine_ttft_seconds",
            "Submit-to-first-token latency",
            boundaries=(0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10))
        self._m_itl = get_histogram(
            "ray_tpu_serve_engine_itl_seconds",
            "Inter-token latency during decode",
            boundaries=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 1))
        import os

        self._pid_tags = {"pid": str(os.getpid())}
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="llm-engine")
        self._thread.start()

    # ------------------------------------------------------------- client API

    def submit(self, prompt_tokens, max_new_tokens: int = 16,
               temperature: float = 0.0,
               stop_token: Optional[int] = None) -> TokenStream:
        """Queue one sequence; returns its token stream.  Sheds with
        :class:`EngineOverloadedError` when the wait queue is full."""
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if prompt.size == 0 or prompt.size > self.config.max_prompt_len:
            raise ValueError(
                f"prompt length {prompt.size} outside (0, "
                f"{self.config.max_prompt_len}]")
        max_new = min(int(max_new_tokens), self.config.max_new_tokens_cap)
        if max_new <= 0:
            raise ValueError("max_new_tokens must be positive")
        need = math.ceil((prompt.size + max_new) / self.config.page_size)
        if need > self.allocator.total:
            raise ValueError(
                f"request needs {need} KV pages but the pool holds only "
                f"{self.allocator.total} — raise EngineConfig.num_pages")
        with self._lock:
            if self._stop:
                raise RuntimeError("engine is shut down")
            if len(self._pending) >= self.config.max_queue:
                self.shed += 1
                self._m_shed.inc(1)
                raise EngineOverloadedError(
                    f"engine queue full ({self.config.max_queue} waiting)")
            self._req_counter += 1
            req = _Request(self._req_counter, prompt, max_new,
                           float(temperature), stop_token)
            # Capture the submitter's trace context (the replica's
            # execution span in the serve path): the loop thread emits
            # this request's queue/prefill/decode spans against it.
            from ..util import tracing

            req.trace_ctx = tracing.context_for_submit()
            req.submit_wall = time.time()
            self._pending.append(req)
            self._m_queue.set(len(self._pending), tags=self._pid_tags)
            self._wake.notify()
        return TokenStream(self, req)

    def cancel(self, req: _Request) -> None:
        """Idempotent; a finished request is a no-op.  Pages return to
        the free list at the loop's next step boundary."""
        req.cancelled.set()
        with self._lock:
            self._wake.notify()

    def shutdown(self) -> None:
        with self._lock:
            self._stop = True
            self._wake.notify()
        self._thread.join(timeout=10)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            queued = len(self._pending)
        active = sum(1 for s in self.slots if s is not None)
        from ..models.paged import trace_count

        return {
            "steps": self.step_count,
            "active_seqs": active,
            "queued": queued,
            "free_pages": self.allocator.free_count,
            "total_pages": self.allocator.total,
            "completed": self.completed,
            "shed": self.shed,
            "cancelled": self.cancelled_count,
            "decode_traces": trace_count("decode"),
            "prefill_traces": trace_count("prefill"),
            "mode": self.config.mode,
        }

    def warmup(self) -> None:
        """Compile the decode program and every prefill bucket up front
        (one dummy sequence per bucket) so serving traffic never pays a
        trace."""
        # max_new_tokens=2: the first token comes from PREFILL — the
        # decode program only compiles once a second token is needed.
        probe = self.submit([1], max_new_tokens=2)
        for _ in probe:
            pass
        for bucket in self.config.prefill_buckets()[1:]:
            n = min(bucket, self.config.max_prompt_len)
            s = self.submit(np.ones((n,), np.int32), max_new_tokens=1)
            for _ in s:
                pass

    # ---------------------------------------------------------------- loop

    def _bucket_len(self, n: int) -> int:
        for b in self.config.prefill_buckets():
            if b >= n:
                return b
        return self.config.prefill_buckets()[-1]

    def _admit_locked(self) -> List[_Request]:
        """Move queued requests into free slots (called under the lock).
        Continuous mode admits whenever a slot AND pages are free;
        whole-request mode admits a full gang only into an EMPTY batch."""
        admitted: List[_Request] = []
        whole = self.config.mode == "whole_request"
        if whole and any(s is not None for s in self.slots):
            return admitted
        for slot in range(self.config.batch_slots):
            if self.slots[slot] is not None or not self._pending:
                continue
            req = self._pending[0]
            need = math.ceil((req.prompt.size + req.max_new)
                             / self.config.page_size)
            pages = self.allocator.alloc(need)
            if pages is None:
                break  # pool pressure: leave queued, retry next step
            self._pending.pop(0)
            req.admit_wall = time.time()
            req.pages = pages
            pt = np.full((self.maxp,), self.scratch, np.int32)
            pt[:need] = pages
            req.page_table = pt
            req.slot = slot
            self.slots[slot] = req
            admitted.append(req)
        if admitted:
            self._m_queue.set(len(self._pending), tags=self._pid_tags)
        return admitted

    def _emit_req_span(self, req: _Request, name: str, start: float,
                       end: float, **attrs) -> None:
        """One request-stage span (queue / prefill / decode), parented to
        the submitter's context.  Buffered emission (util/tracing ring) —
        the decode loop never pays a head RPC for tracing."""
        if req.trace_ctx is None or start <= 0:
            return
        from ..util import tracing

        tracing.emit_span(
            tracing.make_span(req.trace_ctx, name, start, end, **attrs))

    def _evict(self, slot: int, reason: str) -> None:
        req = self.slots[slot]
        assert req is not None
        # Decode-lifetime span: first token -> eviction.  Token count,
        # TTFT, and mean ITL ride as attrs so per-request latency
        # attribution is derivable from the span tree alone.
        now_wall = time.time()
        self._emit_req_span(
            req, "engine:decode", req.first_wall or req.admit_wall,
            now_wall, tokens=req.generated, reason=reason,
            ttft_s=round(req.first_token_t - req.submit_t, 6)
            if req.first_token_t is not None else None,
            mean_itl_s=round(sum(req.itls) / len(req.itls), 6)
            if req.itls else None)
        self.allocator.free(req.pages)
        req.pages = []
        req.finished = True
        self.slots[slot] = None
        self._page_tables[slot, :] = self.scratch
        self._seq_lens[slot] = 0
        self._tokens[slot] = 0
        self._active[slot] = False
        self._temps[slot] = 0.0
        self._dirty = True
        if reason == "cancelled":
            self.cancelled_count += 1
            self._m_cancelled.inc(1)
        elif reason in ("complete", "stop"):
            self.completed += 1
            self._m_completed.inc(1)
        if reason == "shutdown":
            # Loudly: a truncated generation must not look complete.
            req.out_q.put(("err", RuntimeError(
                "engine shut down mid-generation"), self.step_count))
        else:
            req.out_q.put(("done", reason, self.step_count))

    def _prefill(self, req: _Request) -> None:
        """Run one admitted sequence's prompt through the bucketed
        prefill program and emit its first token (TTFT point)."""
        import jax.numpy as jnp

        from ..models.paged import paged_prefill

        n = req.prompt.size
        s_pad = self._bucket_len(n)
        req.prefill_bucket = s_pad
        # Queue-wait span (submit -> admission into a batch slot).
        self._emit_req_span(req, "engine:queue", req.submit_wall,
                            req.admit_wall or req.submit_wall,
                            prompt_len=int(n))
        pf_start = time.time()
        toks = np.zeros((1, s_pad), np.int32)
        toks[0, :n] = req.prompt
        first, self._d_key, self.pools = paged_prefill(
            self.model_config, self.params, self.pools,
            jnp.asarray(toks), jnp.asarray(n, jnp.int32),
            jnp.asarray(req.page_table),
            jnp.asarray(req.temperature, jnp.float32), self._d_key)
        first = int(first)
        now = time.perf_counter()
        req.length = n
        req.first_token_t = now
        req.last_token_t = now
        req.first_wall = time.time()
        # Prefill span, bucket attr included: bucket-vs-prompt padding
        # waste is readable straight off the trace.
        self._emit_req_span(req, "engine:prefill", pf_start, req.first_wall,
                            bucket=int(s_pad), prompt_len=int(n))
        self._m_prefill.inc(n)
        self._m_ttft.observe(now - req.submit_t)
        slot = req.slot
        self._page_tables[slot] = req.page_table
        self._seq_lens[slot] = n
        self._tokens[slot] = first
        self._active[slot] = True
        self._temps[slot] = req.temperature
        self._dirty = True
        self._emit_token(req, first)

    def _emit_token(self, req: _Request, token: int) -> None:
        req.generated += 1
        self._m_tokens.inc(1)
        req.out_q.put(("tok", token, self.step_count))
        if req.stop_token is not None and token == req.stop_token:
            self._evict(req.slot, "stop")
        elif req.generated >= req.max_new:
            self._evict(req.slot, "complete")

    def _fail_inflight(self, exc: BaseException) -> None:
        """A model-call failure must not kill the loop thread silently:
        every in-flight request gets the error on its stream, pages
        return to the free list, and the pools are rebuilt (a failed
        donated call may have invalidated them).  Queued requests stay
        queued — they retry against the fresh pool."""
        from ..models.paged import init_paged_pools

        now_wall = time.time()
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            self._emit_req_span(
                req, "engine:decode",
                req.first_wall or req.admit_wall or req.submit_wall,
                now_wall, tokens=req.generated, reason="error",
                error=repr(exc)[:200])
            self.allocator.free(req.pages)
            req.pages = []
            req.finished = True
            self.slots[slot] = None
            req.out_q.put(("err", exc, self.step_count))
        self._page_tables[:] = self.scratch
        self._seq_lens[:] = 0
        self._tokens[:] = 0
        self._active[:] = False
        self._temps[:] = 0.0
        self._dirty = True
        self.pools = init_paged_pools(
            self.model_config, self.config.pool_pages,
            self.config.page_size)

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._stop:
                    break
                # Reap cancellations first: queued cancels just drop,
                # in-flight cancels free pages before admission looks at
                # the pool.
                keep = []
                for r in self._pending:
                    if r.cancelled.is_set():
                        self.cancelled_count += 1
                        self._m_cancelled.inc(1)
                        r.out_q.put(("done", "cancelled", self.step_count))
                    else:
                        keep.append(r)
                if len(keep) != len(self._pending):
                    self._m_queue.set(len(keep), tags=self._pid_tags)
                self._pending = keep
                for slot, req in enumerate(self.slots):
                    if req is not None and req.cancelled.is_set():
                        self._evict(slot, "cancelled")
                admitted = self._admit_locked()
                active = sum(1 for s in self.slots if s is not None)
                if not admitted and active == 0:
                    self._m_active.set(0, tags=self._pid_tags)
                    self._m_pages.set(self.allocator.used_count,
                                      tags=self._pid_tags)
                    self._wake.wait(timeout=0.05)
                    continue
            # Model work runs OUTSIDE the lock: pools/slot arrays belong
            # to this thread; submit() only appends to the wait queue.
            try:
                self._run_step(admitted)
            except Exception as e:  # noqa: BLE001 — fail streams, not
                self._fail_inflight(e)  # the loop thread
        # Shutdown: fail queued + in-flight requests loudly.
        with self._lock:
            pending, self._pending = self._pending, []
            self._m_queue.set(0, tags=self._pid_tags)
        for req in pending:
            req.out_q.put(("err", RuntimeError(
                "engine shut down before admission"), self.step_count))
        for slot, req in enumerate(self.slots):
            if req is not None:
                self._evict(slot, "shutdown")

    def _run_step(self, admitted: List[_Request]) -> None:
        import jax.numpy as jnp

        from ..models.paged import paged_decode_step

        for req in admitted:
            self._prefill(req)
        if not any(s is not None for s in self.slots):
            return
        self.step_count += 1
        if self._dirty:
            # Membership changed since the last step: re-upload the
            # host mirrors.  Steady-state decode skips this — tokens,
            # lengths, and the PRNG key advance on device.
            self._d_tokens = jnp.asarray(self._tokens)
            self._d_page_tables = jnp.asarray(self._page_tables)
            self._d_seq_lens = jnp.asarray(self._seq_lens)
            self._d_active = jnp.asarray(self._active)
            self._d_temps = jnp.asarray(self._temps)
            self._dirty = False
        (self._d_tokens, self._d_seq_lens, self._d_key,
         self.pools) = paged_decode_step(
            self.model_config, self.params, self.pools,
            self._d_tokens, self._d_page_tables, self._d_seq_lens,
            self._d_active, self._d_temps, self._d_key)
        toks = np.asarray(self._d_tokens)
        now = time.perf_counter()
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            self._seq_lens[slot] += 1
            req.length += 1
            self._tokens[slot] = toks[slot]
            if req.last_token_t is not None:
                itl = now - req.last_token_t
                req.itls.append(itl)
                self._m_itl.observe(itl)
            req.last_token_t = now
            self._emit_token(req, int(toks[slot]))
        self._m_active.set(
            sum(1 for s in self.slots if s is not None),
            tags=self._pid_tags)
        self._m_pages.set(self.allocator.used_count,
                          tags=self._pid_tags)


# ------------------------------------------------------------ serve binding


_MODEL_BUILDERS = {
    "tiny": lambda: _tiny_config(),
    "b1": lambda: _b1_config(),
}


def _tiny_config():
    import jax.numpy as jnp

    from ..models import LlamaConfig

    return LlamaConfig.tiny(remat=False, dtype=jnp.float32)


def _b1_config():
    import jax.numpy as jnp

    from ..models import LlamaConfig

    return LlamaConfig.b1(remat=False, dtype=jnp.bfloat16)


class LLMServer:
    """The deployment callable: one engine per replica, tokens streamed
    through serve's per-item streaming path (handle iterators, HTTP SSE,
    gRPC server-streaming).  A consumer that disconnects mid-stream
    closes the generator, which cancels the request and frees its pages."""

    def __init__(self, model: str = "tiny",
                 engine: Optional[dict] = None, seed: int = 0,
                 warmup: bool = False):
        import jax

        from ..models import llama_init

        cfg = _MODEL_BUILDERS[model]()
        params = llama_init(cfg, jax.random.PRNGKey(seed))
        self.engine = InferenceEngine(
            cfg, params, EngineConfig(**(engine or {})), seed=seed)
        if warmup:
            self.engine.warmup()

    def __call__(self, prompt_tokens, max_new_tokens: int = 16,
                 temperature: float = 0.0,
                 stop_token: Optional[int] = None):
        stream = self.engine.submit(
            prompt_tokens, max_new_tokens=max_new_tokens,
            temperature=temperature, stop_token=stop_token)
        try:
            for tok in stream:
                yield tok
        finally:
            # Reached on completion AND on GeneratorExit (client gone,
            # task cancelled): idempotent, frees pages mid-flight.
            stream.cancel()

    def stats(self) -> Dict[str, Any]:
        return self.engine.stats()


def llm_app(model: str = "tiny", engine: Optional[dict] = None,
            num_replicas: int = 1, name: str = "llm", seed: int = 0,
            warmup: bool = False):
    """Build a servable LLM application:
    ``serve.run(llm_app(...))`` then stream tokens via
    ``handle.options(stream=True).remote([1, 2, 3], 16)`` or POST with
    ``Accept: text/event-stream``."""
    from .api import Deployment

    dep = Deployment(LLMServer, name, num_replicas=num_replicas)
    return dep.bind(model=model, engine=engine, seed=seed, warmup=warmup)
