"""Declarative Serve config: deploy applications from a YAML/dict spec.

Role-equivalent to the reference's Serve schema + `serve deploy`
(reference: serve/schema.py ServeDeploySchema, scripts `serve deploy` /
`serve status` — the K8s-friendly declarative path where a config file,
not a driver script, is the source of truth).

Config shape::

    applications:
      - name: summarizer                 # serve.run name override
        import_path: my_pkg.app:app      # module:attr -> Application
                                         #   (or Deployment, auto-bound)
        args: {model: "t5-small"}        # bind(**args) when attr is a
                                         #   Deployment
        deployments:                     # per-deployment option overrides
          - name: Summarizer
            num_replicas: 3
            max_concurrent_queries: 16

Apply with :func:`deploy` or ``python -m ray_tpu serve deploy config.yaml``.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

from .api import Application, Deployment, run


def _load_import_path(path: str):
    mod_name, _, attr = path.partition(":")
    if not attr:
        raise ValueError(
            f"import_path {path!r} must be '<module>:<attribute>'"
        )
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _apply_overrides(app: Application,
                     overrides: List[Dict[str, Any]]) -> Application:
    """Rebuild the bound graph with per-deployment option overrides applied
    by deployment name (reference: deployments section of the schema
    overrides the code's defaults).  Rebuilding memoizes by node identity
    so serve.run's diamond dedup still sees one shared child as one node;
    override names that match no deployment raise (a YAML typo must not
    silently deploy defaults)."""
    by_name = {o["name"]: {k: v for k, v in o.items() if k != "name"}
               for o in overrides}
    consumed: set = set()
    memo: Dict[int, Application] = {}

    def rebuild(a: Application) -> Application:
        if id(a) in memo:
            return memo[id(a)]
        dep = a.deployment
        opts = by_name.get(dep.name)
        if opts is not None:
            consumed.add(dep.name)
            dep = dep.options(**opts)
        args = tuple(rebuild(x) if isinstance(x, Application) else x
                     for x in a.init_args)
        kwargs = {k: rebuild(v) if isinstance(v, Application) else v
                  for k, v in a.init_kwargs.items()}
        out = Application(dep, args, kwargs)
        memo[id(a)] = out
        return out

    rebuilt = rebuild(app)
    unknown = set(by_name) - consumed
    if unknown:
        raise ValueError(
            f"deployment overrides match nothing in the app graph: "
            f"{sorted(unknown)}"
        )
    return rebuilt


def deploy(config: Dict[str, Any] | str, *, wait_ready: bool = True) -> list:
    """Deploy every application in a config dict or YAML file path.
    Returns the ingress handles in config order."""
    if isinstance(config, str):
        import yaml

        with open(config) as f:
            config = yaml.safe_load(f)
    handles = []
    for app_cfg in config.get("applications", []):
        target = _load_import_path(app_cfg["import_path"])
        if isinstance(target, Deployment):
            target = target.bind(**(app_cfg.get("args") or {}))
        if not isinstance(target, Application):
            raise TypeError(
                f"import_path {app_cfg['import_path']!r} resolved to "
                f"{type(target).__name__}; expected a bound Application or "
                "a Deployment"
            )
        target = _apply_overrides(target, app_cfg.get("deployments") or [])
        handles.append(run(
            target, name=app_cfg.get("name"), wait_ready=wait_ready,
        ))
    return handles
