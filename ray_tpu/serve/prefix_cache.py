"""Radix prefix cache over the paged KV pool.

Role-equivalent to vLLM-style automatic prefix caching / SGLang
RadixAttention as deployed behind Ray Serve LLM (reference: fleets
sharing a system prompt pay one prefill).  Page-aligned prompt prefixes
live in a radix tree: each node owns ONE KV page keyed by that page's
``page_size`` token ids, so walking full-page chunks of a new prompt
yields the longest cached prefix.  The tree holds one allocator ref per
cached page and every sequence that matches takes its own ref
(:meth:`PageAllocator.share`), so a page outlives whichever of
tree/sequences releases it last.

Copy-on-write: when a prompt diverges MID-page from a cached child, the
engine copies that child's page into a fresh private page
(``models/paged.copy_page``) and suffix-prefills from the divergence
point — the cached page is never written after insertion (decode always
appends past the frozen prompt prefix; only fully-frozen pages are
inserted).

Trees are keyed PER ADAPTER: cached V depends on the adapter's wv delta,
so sharing a prefix across adapters would be silently wrong.

Owned by the engine's loop thread like the allocator — no locking here.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

_Key = Tuple[int, ...]


class _Node:
    __slots__ = ("key", "page", "children", "parent", "last_used")

    def __init__(self, key: _Key, page: int, parent: "_Node",
                 last_used: int):
        self.key = key
        self.page = page
        self.children: Dict[_Key, "_Node"] = {}
        self.parent = parent
        self.last_used = last_used


@dataclasses.dataclass
class PrefixMatch:
    """Result of a lookup.  ``pages`` are fully-matched cached pages
    (tokens ``[0, matched_len)``); ``cow_src``/``cow_overlap`` describe a
    mid-page divergence: copy ``cow_src`` and keep its first
    ``cow_overlap`` token positions.  ``prefix_len`` is what the suffix
    prefill skips.  Take refs via :meth:`RadixPrefixCache.claim` before
    touching any of these pages."""

    pages: List[int]
    matched_len: int
    cow_src: Optional[int] = None
    cow_overlap: int = 0
    _nodes: List[_Node] = dataclasses.field(default_factory=list)

    @property
    def prefix_len(self) -> int:
        return self.matched_len + self.cow_overlap

    @property
    def hit(self) -> bool:
        return self.prefix_len > 0


class RadixPrefixCache:
    def __init__(self, page_size: int):
        self.page_size = page_size
        self._roots: Dict[Optional[str], _Node] = {}
        self._clock = 0
        self.pages = 0          # pages the tree currently holds refs on
        self.hits = 0           # lookups that matched >= 1 token
        self.lookups = 0
        self.inserts = 0        # pages inserted
        self.evicted = 0        # pages released by leaf eviction

    def _root(self, adapter: Optional[str]) -> _Node:
        r = self._roots.get(adapter)
        if r is None:
            r = self._roots[adapter] = _Node((), -1, None, 0)  # type: ignore[arg-type]
        return r

    # -------------------------------------------------------------- lookup

    def lookup(self, adapter: Optional[str], tokens) -> PrefixMatch:
        """Longest cached prefix of ``tokens`` under ``adapter``'s tree.
        Pure (no refcount changes).  At least one suffix token is always
        left unmatched — the first sampled token needs real logits, so a
        full-prompt hit is capped one position short."""
        toks: _Key = tuple(int(t) for t in tokens)
        ps = self.page_size
        self.lookups += 1
        node = self._root(adapter)
        pages: List[int] = []
        nodes: List[_Node] = []
        matched = 0
        while matched + ps < len(toks):
            child = node.children.get(toks[matched:matched + ps])
            if child is None:
                break
            pages.append(child.page)
            nodes.append(child)
            node = child
            matched += ps
        # Mid-page divergence: the child sharing the longest proper
        # token-prefix with the remainder is the COW source.
        rem = toks[matched:]
        cow_src, cow_overlap, cow_node = None, 0, None
        cap = min(ps, len(rem) - 1)  # keep >= 1 suffix token
        if cap > 0:
            for key, child in node.children.items():
                ov = 0
                for a, b in zip(key, rem):
                    if a != b:
                        break
                    ov += 1
                ov = min(ov, cap)
                if ov > cow_overlap:
                    cow_overlap, cow_src, cow_node = ov, child.page, child
        m = PrefixMatch(pages, matched, cow_src, cow_overlap,
                        _nodes=nodes + ([cow_node] if cow_node else []))
        if m.hit:
            self.hits += 1
        return m

    def claim(self, match: PrefixMatch, allocator) -> None:
        """Take one sequence ref per matched page (including the COW
        source — it must survive until the engine copies it) and bump
        recency on the matched path."""
        held = list(match.pages)
        if match.cow_src is not None:
            held.append(match.cow_src)
        allocator.share(held)
        self._clock += 1
        for n in match._nodes:
            n.last_used = self._clock

    # -------------------------------------------------------------- insert

    def insert(self, adapter: Optional[str], tokens, pages: List[int],
               allocator) -> int:
        """Insert a freshly-prefilled prompt's FULL pages (``pages[i]``
        holds tokens ``[i*ps, (i+1)*ps)``).  Existing nodes dedupe — the
        tree keeps its first copy and takes no ref on the newcomer's
        page.  The trailing partial page is never inserted: decode still
        appends to it.  Returns pages newly cached."""
        toks: _Key = tuple(int(t) for t in tokens)
        ps = self.page_size
        node = self._root(adapter)
        self._clock += 1
        added = 0
        for i in range(len(toks) // ps):
            key = toks[i * ps:(i + 1) * ps]
            child = node.children.get(key)
            if child is None:
                child = _Node(key, pages[i], node, self._clock)
                node.children[key] = child
                allocator.share([pages[i]])
                self.pages += 1
                self.inserts += 1
                added += 1
            child.last_used = self._clock
            node = child
        return added

    # ------------------------------------------------------------- eviction

    def evict_leaves(self, want: int, allocator) -> int:
        """Release up to ``want`` tree-held pages, LRU leaves first.
        Only leaves whose page the tree holds the LAST ref on count —
        freeing a page a live sequence still reads returns nothing to
        the free list (and discards reusable cache for no gain), so
        those leaves are left alone.  Interior nodes are positional:
        a child's page is meaningless without its parent, so eviction
        never orphans a subtree."""
        freed = 0
        while freed < want:
            leaves = [n for n in self._walk()
                      if not n.children and allocator.refs(n.page) == 1]
            if not leaves:
                break
            leaves.sort(key=lambda n: n.last_used)
            for n in leaves:
                if freed >= want:
                    break
                del n.parent.children[n.key]
                allocator.free([n.page])
                self.pages -= 1
                self.evicted += 1
                freed += 1
        return freed

    def drop_adapter(self, adapter: Optional[str], allocator) -> int:
        """Release every page under one adapter's tree (the adapter's
        weights changed — its cached V deltas are stale)."""
        root = self._roots.pop(adapter, None)
        n = 0
        if root is not None:
            for node in self._walk_from(root):
                allocator.free([node.page])
                self.pages -= 1
                n += 1
        return n

    def clear(self, allocator) -> int:
        """Release every tree-held ref (pool rebuild, drain-to-balance
        in tests/bench)."""
        n = 0
        for adapter in list(self._roots):
            n += self.drop_adapter(adapter, allocator)
        return n

    # ---------------------------------------------------------------- misc

    def _walk(self):
        for root in self._roots.values():
            yield from self._walk_from(root)

    def _walk_from(self, root: _Node):
        stack = list(root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            yield n

    def stats(self) -> Dict[str, Any]:
        return {
            "pages": self.pages,
            "hits": self.hits,
            "lookups": self.lookups,
            "inserts": self.inserts,
            "evicted": self.evicted,
            "trees": len(self._roots),
        }
