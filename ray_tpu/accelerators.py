"""TPU accelerator support: chip autodetect, visibility isolation, pod-slice
resources.

Role-equivalent to the reference's pluggable accelerator managers
(reference: python/ray/_private/accelerators/accelerator.py,
tpu.py:71 TPUAcceleratorManager) — re-designed for this framework:

- **Autodetect** (`num_chips`): counts ``/dev/accel*`` then ``/dev/vfio/<n>``
  device files (reference: tpu.py:97-117).  ``RT_TPU_CHIPS`` overrides for
  tests and for operators who want to advertise fewer chips than the host has.
- **Pod-slice resources** (`node_resources`): a host that knows its pod type
  (``TPU_ACCELERATOR_TYPE`` env, GKE-style) advertises ``TPU-<version>``
  (e.g. ``TPU-V5E``) alongside the ``TPU`` chip count, and worker 0 of a pod
  advertises the ``TPU-<pod_type>-head`` marker resource so exactly one
  framework task can claim slice leadership (reference: tpu.py:198-314).
  GCE metadata-server probing is gated behind ``RT_TPU_GCE_METADATA=1``
  because this build targets zero-egress environments.
- **Visibility isolation** (`visibility_env`): a task that requests
  ``{"TPU": n}`` with n < host chips gets ``TPU_VISIBLE_CHIPS`` plus the
  chip/host-bounds variables that make libtpu carve out a sub-host topology
  (reference: tpu.py:155-196; the 1-chip and 2-chip bounds come from the
  jax#14977 recipe).  n == all chips clears the bounds so JAX uses the
  host defaults.

The head's scheduler owns the per-node chip-ID pool (scheduler.py
``allocate_tpu_chips``); the worker applies the env right before running the
task's function, i.e. before user code first imports jax.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List, Optional

TPU_VALID_CHIP_OPTIONS = (1, 2, 4, 8)

#: Versions whose devices expose 2 cores per chip (affects pod host math).
_MULTI_CORE_VERSIONS = {"v2", "v3", "v4"}

_POD_TYPE_RE = re.compile(r"^v\d+[a-zA-Z]*-\d+$")


def num_chips() -> int:
    """Number of TPU chips attached to this host (0 when none)."""
    override = os.environ.get("RT_TPU_CHIPS")
    if override is not None:
        try:
            return max(0, int(override))
        except ValueError:
            return 0
    n = len(glob.glob("/dev/accel*"))
    if n:
        return n
    try:
        return sum(1 for e in os.listdir("/dev/vfio") if e.isdigit())
    except (FileNotFoundError, NotADirectoryError, PermissionError):
        return 0


def is_valid_pod_type(pod_type: str) -> bool:
    """``v<generation>-<chips_or_cores>``, e.g. ``v5e-8`` / ``v4-16``."""
    return bool(_POD_TYPE_RE.match(pod_type))


def pod_type() -> Optional[str]:
    """The pod/slice type this host belongs to, if known."""
    t = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    if not t and os.environ.get("RT_TPU_GCE_METADATA") == "1":
        t = _gce_metadata("accelerator-type") or ""
    return t if t and is_valid_pod_type(t) else None


def tpu_name() -> Optional[str]:
    name = os.environ.get("TPU_NAME")
    if not name and os.environ.get("RT_TPU_GCE_METADATA") == "1":
        name = _gce_metadata("instance-id")
    return name or None


def worker_id() -> Optional[int]:
    wid = os.environ.get("TPU_WORKER_ID")
    if not wid and os.environ.get("RT_TPU_GCE_METADATA") == "1":
        wid = _gce_metadata("agent-worker-number")
    try:
        return int(wid) if wid else None
    except ValueError:
        return None


def _gce_metadata(key: str) -> Optional[str]:
    """GCE VM metadata (requires network egress — opt-in only)."""
    import urllib.request

    url = f"http://metadata.google.internal/computeMetadata/v1/instance/attributes/{key}"
    try:
        req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=2) as resp:
            if resp.status == 200:
                return resp.read().decode()
    except Exception:
        pass
    return None


def pod_worker_count(pod: str) -> int:
    """Hosts in a slice of the given pod type (v2-v4 count cores, 8/host;
    later generations count chips, 4/host)."""
    version, _, count = pod.partition("-")
    per_host = 8 if version in _MULTI_CORE_VERSIONS else 4
    return max(1, int(count) // per_host)


def accelerator_type(pod: Optional[str] = None) -> Optional[str]:
    """Version marker resource, e.g. ``TPU-V5E`` (reference: tpu.py:296)."""
    pod = pod or pod_type()
    if not pod:
        return None
    return "TPU-" + pod.split("-")[0].upper()


def validate_request(quantity: float) -> Optional[str]:
    """None when ``quantity`` is a supported per-task chip count, else an
    error message.  Fractional requests time-share one chip and are allowed."""
    if 0 < quantity < 1:
        return None
    if quantity in TPU_VALID_CHIP_OPTIONS:
        return None
    return (
        f"requested TPU={quantity}, but only {TPU_VALID_CHIP_OPTIONS} (or a "
        "fraction < 1) map to valid per-host chip topologies"
    )


def node_resources() -> Dict[str, float]:
    """Resources a node daemon should auto-advertise for its TPUs."""
    n = num_chips()
    if n == 0:
        return {}
    res: Dict[str, float] = {"TPU": float(n)}
    pod = pod_type()
    acc = accelerator_type(pod)
    if acc:
        res[acc] = float(n)
    if pod and (worker_id() or 0) == 0:
        res[f"TPU-{pod}-head"] = 1.0
    return res


def node_labels() -> Dict[str, str]:
    """Topology labels for affinity scheduling (slice name + host index)."""
    labels: Dict[str, str] = {}
    pod = pod_type()
    if pod:
        labels["tpu-pod-type"] = pod
    name = tpu_name()
    if name:
        labels["tpu-name"] = name
    wid = worker_id()
    if wid is not None:
        labels["tpu-worker-id"] = str(wid)
    return labels


def visibility_env(chip_ids: List[int], host_chips: Optional[int] = None) -> Dict[str, str]:
    """Env vars granting a process exactly ``chip_ids``.

    Empty-string values mean "unset this variable" (the worker applies them
    with ``os.environ.pop``).  Granting every chip on the host clears the
    sub-host bounds so libtpu uses its defaults.
    """
    if host_chips is None:
        host_chips = num_chips()
    n = len(chip_ids)
    if n == 0 or n == host_chips:
        return {
            "TPU_VISIBLE_CHIPS": "",
            "TPU_CHIPS_PER_HOST_BOUNDS": "",
            "TPU_HOST_BOUNDS": "",
        }
    env = {"TPU_VISIBLE_CHIPS": ",".join(str(c) for c in sorted(chip_ids))}
    if n == 1:
        env["TPU_CHIPS_PER_HOST_BOUNDS"] = "1,1,1"
        env["TPU_HOST_BOUNDS"] = "1,1,1"
    elif n == 2:
        env["TPU_CHIPS_PER_HOST_BOUNDS"] = "1,2,1"
        env["TPU_HOST_BOUNDS"] = "1,1,1"
    # 4-chip grants on an 8-chip host inherit default bounds: there is no
    # single sub-host topology that covers both v5e (2x4) and v6e layouts,
    # so only TPU_VISIBLE_CHIPS narrows the view.
    return env


def apply_visibility(chip_ids: List[int], host_chips: Optional[int] = None) -> None:
    """Apply `visibility_env` to this process.  Must run before the first
    ``import jax`` to take effect (reference applies the same env dance at
    task start: tpu.py:155 set_current_process_visible_accelerator_ids)."""
    for k, v in visibility_env(chip_ids, host_chips).items():
        if v == "":
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    if chip_ids:
        # The worker was spawned with JAX_PLATFORMS=cpu so it could not steal
        # the host's chips; a task granted chips flips back to TPU.
        os.environ["JAX_PLATFORMS"] = "tpu,cpu"
