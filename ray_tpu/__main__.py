import sys

from .scripts import main

sys.exit(main())
